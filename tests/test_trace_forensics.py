"""Trace-forensics tests (ISSUE 4): trace-indexed span store, cross-node
span assembly through a real proxy + 2-backend topology, tail-based
slow-log capture, Prometheus exemplars, the runtime telemetry sampler,
and get_spans/get_slow_log envelope compat on both transports."""

from __future__ import annotations

import re
import threading
import time

import pytest

from jubatus_tpu.utils import tracing

CONF = {
    "method": "PA",
    "parameter": {"regularization_weight": 1.0},
    "converter": {"num_rules": [{"key": "*", "type": "num"}]},
}


# -- span store ---------------------------------------------------------------


def test_span_store_indexed_by_trace():
    reg = tracing.Registry()
    ctx_a = tracing.new_root()
    ctx_b = tracing.new_root()
    with tracing.use_trace(ctx_a):
        reg.record("rpc.x", 0.001)
        reg.record("rpc.y", 0.002)
    with tracing.use_trace(ctx_b):
        reg.record("rpc.x", 0.003)
    spans_a = reg.get_spans(ctx_a.trace_id)
    assert [s["name"] for s in spans_a] == ["rpc.x", "rpc.y"]
    assert all(s["trace_id"] == ctx_a.trace_id for s in spans_a)
    assert len(reg.get_spans(ctx_b.trace_id)) == 1
    assert reg.get_spans("nope") == []


def test_span_store_ring_evicts_oldest_and_prunes_index():
    reg = tracing.Registry(span_capacity=8)
    first = tracing.new_root()
    with tracing.use_trace(first):
        reg.record("rpc.old", 0.001)
    for _ in range(20):
        with tracing.use_trace(tracing.new_root()):
            reg.record("rpc.new", 0.001)
    assert len(reg.recent_spans()) == 8
    # the evicted trace's index entry is gone, not leaked
    assert reg.get_spans(first.trace_id) == []
    assert len(reg._by_trace) == 8


def test_span_parent_edges_from_child_context():
    root = tracing.new_root()
    child = tracing.child_of(root)
    assert child.trace_id == root.trace_id
    assert child.parent_id == root.span_id
    assert child.span_id != root.span_id


def test_span_handle_cancel_suppresses_record():
    reg = tracing.Registry()
    with reg.span("kept"):
        pass
    with reg.span("dropped") as sp:
        sp.cancel()
    st = reg.trace_status()
    assert st["trace.kept.count"] == 1
    assert "trace.dropped.count" not in st
    assert sp.seconds >= 0.0  # duration still measured for the caller


def test_forensics_toggle_keeps_histograms():
    reg = tracing.Registry()
    reg.slowlog.configure(min_count=1, quantile=0.5)
    reg.set_forensics(False)
    ctx = tracing.new_root()
    with tracing.use_trace(ctx):
        for _ in range(10):
            reg.record("rpc.z", 0.001)
    assert reg.trace_status()["trace.rpc.z.count"] == 10
    assert reg.get_spans(ctx.trace_id) == []
    assert reg.slowlog.snapshot() == []


# -- slow log -----------------------------------------------------------------


def test_slowlog_threshold_behavior():
    """No capture below min_count; past it, only requests at/above the
    configured quantile of their OWN histogram land in the ring."""
    reg = tracing.Registry()
    reg.slowlog.configure(capacity=16, quantile=0.99, min_count=64)
    ctx = tracing.new_root()
    with tracing.use_trace(ctx):
        # spread 1..32 ms: p99 lands near the top of the spread, so a
        # clearly-median request afterwards must NOT be captured
        for i in range(64):
            reg.record("rpc.t", 0.001 * (1 + i % 32))
        base = len(reg.slowlog.snapshot())
        reg.record("rpc.t", 0.002)  # ~median: under the p99 threshold
        assert len(reg.slowlog.snapshot()) == base
        reg.record("rpc.t", 1.0)  # unambiguous tail event
    recs = reg.slowlog.snapshot()
    assert len(recs) == base + 1
    slow = recs[-1]
    assert slow["method"] == "rpc.t"
    assert slow["duration_ms"] == pytest.approx(1000.0, rel=0.01)
    assert slow["trace_id"] == ctx.trace_id
    assert slow["threshold_ms"] > 2.0
    assert "peer" in slow and "ts" in slow


def test_slowlog_no_capture_below_min_count():
    reg = tracing.Registry()
    reg.slowlog.configure(capacity=16, quantile=0.99, min_count=64)
    for _ in range(63):
        reg.record("rpc.m", 0.001)
    assert reg.slowlog.snapshot() == []


def test_slowlog_ring_bounded():
    reg = tracing.Registry()
    reg.slowlog.configure(capacity=4, quantile=0.5, min_count=1)
    for _ in range(50):
        reg.record("rpc.b", 0.001)
    stats = reg.slowlog.stats()
    assert stats["retained"] <= 4
    assert stats["captured"] >= stats["retained"]


def test_slowlog_records_deadline_remaining():
    from jubatus_tpu.rpc import deadline as deadlines

    reg = tracing.Registry()
    reg.slowlog.configure(capacity=8, quantile=0.5, min_count=1)
    with deadlines.deadline_after(30.0):
        for _ in range(3):
            reg.record("rpc.d", 0.001)
    recs = reg.slowlog.snapshot()
    assert recs, "quantile 0.5 with min_count 1 must capture"
    assert 0 < recs[-1]["deadline_remaining_ms"] <= 30_000


# -- prometheus exemplars -----------------------------------------------------

#: exposition line with an OpenMetrics-style exemplar:
#:   name{labels} value # {trace_id="..."} exemplar_value timestamp
_EXEMPLAR_RE = re.compile(
    r'^jubatus_span_duration_seconds_bucket\{[^}]*\} \d+ '
    r'# \{trace_id="([0-9a-f]+)"\} [0-9eE.+-]+ [0-9.]+$')


def test_prometheus_exemplar_line_parses():
    reg = tracing.Registry()
    reg.slowlog.configure(min_count=1, quantile=0.5)
    ctx = tracing.new_root()
    with tracing.use_trace(ctx):
        for _ in range(5):
            reg.record("rpc.e", 0.002)
        reg.record("rpc.e", 0.5)  # forced-slow
    text = reg.prometheus_text({"node": "n1"})
    ex_lines = [ln for ln in text.splitlines() if "# {trace_id=" in ln]
    assert ex_lines, text
    m = _EXEMPLAR_RE.match(ex_lines[-1])
    assert m, ex_lines[-1]
    assert m.group(1) == ctx.trace_id
    # non-exemplar lines still parse as plain format 0.0.4
    for ln in text.splitlines():
        if ln.startswith("#") or not ln or "# {" in ln:
            continue
        assert re.match(r'^[a-zA-Z_:][a-zA-Z0-9_:]*(\{[^}]*\})? '
                        r'[0-9eE.+-]+$', ln), ln


# -- runtime telemetry --------------------------------------------------------


def test_runtime_telemetry_sampler_keys():
    from jubatus_tpu.utils.runtime_telemetry import RuntimeTelemetry

    reg = tracing.Registry()
    rt = RuntimeTelemetry(reg, interval_sec=0.05)
    s = rt.sample()
    for key in ("rss_bytes", "open_fds", "threads", "gc_gen0",
                "slowlog_depth", "samples"):
        assert key in s, s
    # jax is imported by the test session -> the jax keys must be present
    assert "jax_compile_count" in s and "jax_compile_ms" in s
    # gauges reach the registry -> /metrics exposition
    text = reg.prometheus_text()
    assert 'jubatus_runtime_gauge{key="rss_bytes"}' in text
    # the sampler thread keeps sampling
    rt.start()
    try:
        deadline = time.monotonic() + 3.0
        while time.monotonic() < deadline:
            if rt.status().get("samples", 0) >= 2:
                break
            time.sleep(0.02)
        assert rt.status()["samples"] >= 2
    finally:
        rt.stop()


def test_jax_compile_hook_counts_compiles():
    import jax
    import jax.numpy as jnp

    from jubatus_tpu.utils import runtime_telemetry as rtm

    assert rtm.install_jax_hooks()
    before = rtm.jax_compile_stats()["compile_count"]
    # a fresh closure defeats the jit cache -> at least one real compile
    k = time.monotonic()  # unique constant baked into the jaxpr
    jax.jit(lambda x: x * k + 1.0)(jnp.ones(3)).block_until_ready()
    after = rtm.jax_compile_stats()
    assert after["compile_count"] > before
    assert after["compile_ms"] > 0


# -- cross-node assembly ------------------------------------------------------


@pytest.fixture()
def proxy_two_backends():
    from jubatus_tpu.coord.memory import MemoryCoordinator, _Store
    from jubatus_tpu.server import EngineServer
    from jubatus_tpu.server.args import ServerArgs
    from jubatus_tpu.server.proxy import Proxy, ProxyArgs

    store = _Store()
    servers = []
    for _ in range(2):
        srv = EngineServer(
            "classifier", CONF,
            args=ServerArgs(engine="classifier", coordinator="(shared)",
                            name="fx", listen_addr="127.0.0.1",
                            interval_sec=1e9, interval_count=1 << 30),
            coord=MemoryCoordinator(store))
        srv.start(0)
        servers.append(srv)
    proxy = Proxy(ProxyArgs(engine="classifier", listen_addr="127.0.0.1"),
                  coord=MemoryCoordinator(store))
    proxy.start(0)
    yield servers, proxy
    proxy.stop()
    for s in servers:
        s.stop()


def test_cross_node_span_assembly(proxy_two_backends):
    """ISSUE 4 acceptance: ONE trace_id through proxy + 2 backends
    assembles into a single tree with >= 3 hops (proxy dispatch ->
    per-backend client calls -> backend dispatches)."""
    from jubatus_tpu.cmd.jubactl import assemble_trace
    from jubatus_tpu.rpc.client import RpcClient

    servers, proxy = proxy_two_backends
    ctx = tracing.new_root()
    with tracing.use_trace(ctx):
        with RpcClient("127.0.0.1", proxy.args.rpc_port) as c:
            assert c.call("get_status", "fx")
    # one get_spans against the PROXY returns proxy + backend records
    with RpcClient("127.0.0.1", proxy.args.rpc_port) as c:
        spans_map = c.call("get_spans", "fx", ctx.trace_id)
    assert len(spans_map) == 3, sorted(spans_map)  # proxy + 2 backends
    spans = []
    for node, recs in spans_map.items():
        assert recs, f"{node} returned no spans"
        for rec in recs:
            rec = dict(rec)
            rec.setdefault("node", node)
            spans.append(rec)
    assert {s["trace_id"] for s in spans} == {ctx.trace_id}
    roots = assemble_trace(spans)
    assert len(roots) == 1, [r["name"] for r in roots]
    root = roots[0]
    assert root["name"] == "rpc.get_status"

    def depth(node, d=1):
        return max([depth(c, d + 1) for c in node["children"]] or [d])

    assert depth(root) >= 3, "proxy -> client-call -> backend hops"
    names = set()

    def walk(node):
        names.add(node["name"])
        for c in node["children"]:
            walk(c)

    walk(root)
    assert "rpc.client.get_status" in names
    # both BACKEND dispatch spans hang off the tree (the third
    # rpc.get_status span is the proxy's own dispatch — the root)
    proxy_node = f"127.0.0.1_{proxy.args.rpc_port}"
    backend_nodes = {s["node"] for s in spans
                     if s["name"] == "rpc.get_status"
                     and s["node"] != proxy_node}
    assert len(backend_nodes) == 2
    assert root["node"] == proxy_node


def test_get_slow_log_rpc_through_proxy(proxy_two_backends):
    from jubatus_tpu.client import ClassifierClient, Datum
    from jubatus_tpu.rpc.client import RpcClient

    servers, proxy = proxy_two_backends
    for s in servers:
        s.rpc.trace.slowlog.configure(min_count=1, quantile=0.5)
    c = ClassifierClient("127.0.0.1", proxy.args.rpc_port, "fx")
    for _ in range(20):
        c.train([["a", Datum({"x": 1.0})]])
    c.close()
    with RpcClient("127.0.0.1", proxy.args.rpc_port) as rc:
        out = rc.call("get_slow_log", "fx")
    # the proxy's own node key is present even if its ring is empty;
    # at least one backend captured something
    assert len(out) == 3, sorted(out)
    captured = [r for recs in out.values() for r in recs]
    assert captured
    assert all("method" in r and "duration_ms" in r and "trace_id" in r
               for r in captured)


def test_jubactl_trace_renders_tree(tmp_path, capsys):
    """jubactl -c trace TRACE_ID against a live 1-proxy/2-backend file-
    coordinator cluster prints ONE assembled tree containing proxy and
    backend spans for the same trace id."""
    from jubatus_tpu.cmd import jubactl
    from jubatus_tpu.rpc.client import RpcClient
    from jubatus_tpu.server import EngineServer
    from jubatus_tpu.server.args import ServerArgs
    from jubatus_tpu.server.proxy import Proxy, ProxyArgs

    coord_dir = str(tmp_path / "coord")
    servers = []
    proxy = None
    try:
        for _ in range(2):
            srv = EngineServer(
                "classifier", CONF,
                args=ServerArgs(engine="classifier", coordinator=coord_dir,
                                name="jt", listen_addr="127.0.0.1",
                                interval_sec=1e9, interval_count=1 << 30))
            srv.start(0)
            servers.append(srv)
        proxy = Proxy(ProxyArgs(engine="classifier",
                                listen_addr="127.0.0.1",
                                coordinator=coord_dir))
        proxy.start(0)
        ctx = tracing.new_root()
        with tracing.use_trace(ctx):
            with RpcClient("127.0.0.1", proxy.args.rpc_port) as c:
                assert c.call("get_status", "jt")
        rc = jubactl.main(["-c", "trace", "-t", "classifier", "-n", "jt",
                           "-z", coord_dir, ctx.trace_id])
        out = capsys.readouterr().out
        assert rc == 0
        assert f"trace {ctx.trace_id}" in out
        assert "1 root(s)" in out, out
        assert "rpc.get_status" in out and "rpc.client.get_status" in out
        # per-hop timings + node attribution are rendered
        assert "ms  @127.0.0.1_" in out and "[t+" in out
        # every node of the topology appears in the tree
        for srv in servers:
            assert f"127.0.0.1_{srv.args.rpc_port}" in out
        assert f"127.0.0.1_{proxy.args.rpc_port}" in out
        # unknown trace id: graceful nonzero exit
        assert jubactl.main(["-c", "trace", "-t", "classifier", "-n", "jt",
                             "-z", coord_dir, "feedfacefeedface"]) == -1
    finally:
        if proxy is not None:
            proxy.stop()
        for s in servers:
            s.stop()


def test_mix_round_spans_share_one_trace():
    """Mix rounds are traces too: the master's mix.round + phase spans
    and the members' mix_* dispatch spans assemble under the trace_id
    stamped into the flight record."""
    from jubatus_tpu.client import ClassifierClient, Datum
    from jubatus_tpu.coord.memory import MemoryCoordinator, _Store
    from jubatus_tpu.server import EngineServer
    from jubatus_tpu.server.args import ServerArgs

    store = _Store()
    servers = []
    try:
        for _ in range(2):
            srv = EngineServer(
                "classifier", CONF,
                args=ServerArgs(engine="classifier", coordinator="(shared)",
                                name="mt", listen_addr="127.0.0.1",
                                interval_sec=1e9, interval_count=1 << 30),
                coord=MemoryCoordinator(store))
            srv.start(0)
            servers.append(srv)
        for s in servers:
            c = ClassifierClient("127.0.0.1", s.args.rpc_port, "mt")
            c.train([["a", Datum({"x": 1.0})]])
            c.close()
        assert servers[0].mixer.mix_now() is not None
        rec = servers[0].mixer.flight.snapshot()[-1]
        assert rec["mode"] == "rpc"
        tid = rec["trace_id"]
        assert tid
        master_spans = servers[0].rpc.trace.get_spans(tid)
        names = {s["name"] for s in master_spans}
        assert "mix.round" in names
        assert "mix.phase.get_diff" in names and "mix.phase.put_diff" in names
        # the member's mix_* dispatches carry the SAME trace id (the
        # fan-out propagates the context across the executor + wire)
        member_spans = servers[1].rpc.trace.get_spans(tid)
        member_names = {s["name"] for s in member_spans}
        assert "rpc.mix_get_diff" in member_names, member_names
        assert "rpc.mix_put_diff" in member_names
    finally:
        for s in servers:
            s.stop()


# -- envelope compat on both transports ---------------------------------------


@pytest.mark.parametrize("native", [False, True])
def test_forensics_rpcs_envelope_compat(monkeypatch, native):
    """get_spans / get_slow_log answer 4-element (plain msgpack-rpc) AND
    5/6-element (traced/deadlined) envelopes on both transports."""
    from jubatus_tpu.rpc import native_server
    from jubatus_tpu.rpc.client import RpcClient
    from jubatus_tpu.server import EngineServer

    if native and not native_server.available():
        pytest.skip("native transport unavailable")
    monkeypatch.setenv("JUBATUS_TPU_NATIVE_RPC", "1" if native else "0")
    srv = EngineServer("classifier", CONF)
    srv.rpc.trace.slowlog.configure(min_count=1, quantile=0.5)
    port = srv.start(0)
    try:
        from jubatus_tpu.client import ClassifierClient, Datum
        from jubatus_tpu.rpc import deadline as deadlines

        c = ClassifierClient("127.0.0.1", port, "")
        for _ in range(5):
            c.train([["a", Datum({"x": 1.0})]])
        c.close()
        ctx = tracing.new_root()
        with tracing.use_trace(ctx):
            with RpcClient("127.0.0.1", port) as rc:
                rc.call("get_status", "")
        with RpcClient("127.0.0.1", port) as rc:
            # plain 4-element envelope
            plain = rc.call("get_spans", "", ctx.trace_id)
            (recs,) = plain.values()
            assert any(r["name"] == "rpc.get_status" for r in recs)
            slow = rc.call("get_slow_log", "")
            (slow_recs,) = slow.values()
            assert slow_recs and all("trace_id" in r for r in slow_recs)
        # traced + deadlined (5/6-element) envelope
        probe = tracing.new_root()
        with tracing.use_trace(probe), deadlines.deadline_after(30.0):
            with RpcClient("127.0.0.1", port) as rc:
                traced = rc.call("get_spans", "", ctx.trace_id)
        (traced_recs,) = traced.values()
        assert {r["span_id"] for r in traced_recs} >= \
            {r["span_id"] for r in recs}
    finally:
        srv.stop()


# -- status / health surfacing ------------------------------------------------


def test_runtime_keys_in_get_status_and_healthz():
    import json
    import urllib.request

    from jubatus_tpu.client import ClassifierClient, Datum
    from jubatus_tpu.server import EngineServer
    from jubatus_tpu.server.args import ServerArgs

    srv = EngineServer(
        "classifier", CONF,
        args=ServerArgs(engine="classifier", listen_addr="127.0.0.1",
                        metrics_port=0))
    port = srv.start(0)
    try:
        c = ClassifierClient("127.0.0.1", port, "")
        c.train([["a", Datum({"x": 1.0})]])
        (st,) = c.get_status().values()
        c.close()
        assert st["runtime.rss_bytes"] > 0
        assert "runtime.jax_compile_count" in st
        assert st["slowlog.capacity"] == 256
        assert st["argv.slowlog_quantile"] == 0.99
        with urllib.request.urlopen(
                f"http://127.0.0.1:{srv.args.metrics_port}/healthz",
                timeout=10) as resp:
            doc = json.loads(resp.read().decode())
        assert doc["rss_bytes"] > 0 and "slowlog_depth" in doc
        with urllib.request.urlopen(
                f"http://127.0.0.1:{srv.args.metrics_port}/slowlog",
                timeout=10) as resp:
            sl = json.loads(resp.read().decode())
        assert sl["stats"]["capacity"] == 256
        assert isinstance(sl["records"], list)
    finally:
        srv.stop()


def test_concurrent_span_store_safe():
    """The trace-indexed store stays consistent under concurrent record
    + get_spans + eviction."""
    reg = tracing.Registry(span_capacity=64)
    stop = threading.Event()
    errors = []

    def pump():
        try:
            while not stop.is_set():
                with tracing.use_trace(tracing.new_root()):
                    reg.record("conc", 1e-4)
        except Exception as e:  # noqa: BLE001
            errors.append(e)

    def read():
        try:
            while not stop.is_set():
                for rec in reg.recent_spans()[:8]:
                    reg.get_spans(rec["trace_id"])
        except Exception as e:  # noqa: BLE001
            errors.append(e)

    threads = [threading.Thread(target=pump) for _ in range(4)] + \
        [threading.Thread(target=read) for _ in range(2)]
    for t in threads:
        t.start()
    time.sleep(0.5)
    stop.set()
    for t in threads:
        t.join(timeout=10)
    assert not errors, errors
    assert len(reg.recent_spans()) <= 64
