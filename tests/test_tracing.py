"""Tracing subsystem tests (SURVEY.md §5: the improvement over the
reference's timing-log-only observability)."""

from __future__ import annotations

import pytest

from jubatus_tpu.utils import tracing


@pytest.fixture(autouse=True)
def _fresh():
    tracing.reset()
    yield
    tracing.reset()


def test_span_aggregates():
    for _ in range(3):
        with tracing.span("unit.op"):
            pass
    st = tracing.trace_status()
    assert st["trace.unit.op.count"] == 3
    assert st["trace.unit.op.mean_ms"] >= 0.0
    assert st["trace.unit.op.max_ms"] >= st["trace.unit.op.mean_ms"]


def test_span_records_on_exception():
    with pytest.raises(ValueError):
        with tracing.span("unit.boom"):
            raise ValueError("x")
    assert tracing.trace_status()["trace.unit.boom.count"] == 1


def test_record_external():
    tracing.record("ext", 0.25)
    st = tracing.trace_status()
    assert st["trace.ext.last_ms"] == 250.0


def test_device_trace_noop_without_dir(monkeypatch):
    monkeypatch.delenv("JUBATUS_TPU_TRACE_DIR", raising=False)
    with tracing.device_trace():
        pass  # must not require jax profiler machinery


def test_device_trace_writes_profile(tmp_path):
    import jax.numpy as jnp

    with tracing.device_trace(str(tmp_path)):
        float(jnp.sum(jnp.ones((8, 8))))
    assert list(tmp_path.rglob("*")), "no profile artifacts written"


def test_rpc_dispatch_records_spans():
    from jubatus_tpu.rpc.client import RpcClient
    from jubatus_tpu.rpc.server import RpcServer

    srv = RpcServer()
    srv.register("ping", lambda: "pong", arity=0)
    port = srv.serve_background(0, host="127.0.0.1")
    try:
        with RpcClient("127.0.0.1", port) as c:
            assert c.call("ping") == "pong"
        st = srv.trace.trace_status()
        assert st["trace.rpc.ping.count"] == 1
    finally:
        srv.stop()


def test_per_server_span_isolation():
    """Two servers in one process must not merge each other's counters."""
    from jubatus_tpu.rpc.client import RpcClient
    from jubatus_tpu.rpc.server import RpcServer

    a, b = RpcServer(), RpcServer()
    a.register("hit", lambda: 1, arity=0)
    b.register("hit", lambda: 2, arity=0)
    pa = a.serve_background(0, host="127.0.0.1")
    b.serve_background(0, host="127.0.0.1")
    try:
        with RpcClient("127.0.0.1", pa) as c:
            c.call("hit")
        assert a.trace.trace_status()["trace.rpc.hit.count"] == 1
        assert "trace.rpc.hit.count" not in b.trace.trace_status()
    finally:
        a.stop(), b.stop()


def test_server_status_includes_traces():
    from jubatus_tpu.server import EngineServer

    conf = {"method": "PA", "parameter": {},
            "converter": {"num_rules": [{"key": "*", "type": "num"}]}}
    srv = EngineServer("classifier", conf)
    from jubatus_tpu.client import ClassifierClient, Datum

    port = srv.start(0)
    try:
        c = ClassifierClient("127.0.0.1", port, "")
        c.train([["a", Datum({"x": 1.0})]])
        (node_st,) = c.get_status().values()
        assert node_st["trace.rpc.train.count"] >= 1
        c.close()
    finally:
        srv.stop()
