"""Usage-attribution plane (ISSUE 19): the 7-element principal
envelope on BOTH transports (4/5/6/7-element frames, old-peer interop,
malformed principals degrading instead of erroring, verbatim C++
relay), the per-tenant ledger end to end on a live server, and the
mergeable get_usage doc fold."""

from __future__ import annotations

import socket

import msgpack
import pytest

from jubatus_tpu.rpc import native_server
from jubatus_tpu.rpc import principal as principals
from jubatus_tpu.rpc.client import RpcClient
from jubatus_tpu.rpc.server import RpcServer

CONF = {"method": "PA", "converter": {
    "num_rules": [{"key": "*", "type": "num"}]}}


def _whoami_server(native: bool):
    srv = native_server.NativeRpcServer() if native else RpcServer()
    srv.register("whoami", lambda: principals.current() or "", arity=0)
    srv.serve_background(0, host="127.0.0.1")
    return srv


def _roundtrip(port: int, frame: bytes):
    s = socket.create_connection(("127.0.0.1", port), timeout=10)
    try:
        s.sendall(frame)
        unp = msgpack.Unpacker(raw=False)
        s.settimeout(10)
        while True:
            data = s.recv(65536)
            assert data, "server closed without answering"
            unp.feed(data)
            for msg in unp:
                return msg
    finally:
        s.close()


@pytest.mark.parametrize("native", [False, True])
def test_envelope_4_to_7_elements_both_transports(native):
    """Every historical envelope shape answers; the 7th element lands
    as the dispatch principal; earlier slots stay nil-paddable."""
    if native and not native_server.available():
        pytest.skip("native transport unavailable")
    srv = _whoami_server(native)
    try:
        cases = [
            ([0, 1, "whoami", []], ""),                      # plain
            ([0, 2, "whoami", [], {}], ""),                  # traced
            ([0, 3, "whoami", [], None, 30.0], ""),          # deadlined
            ([0, 4, "whoami", [], None, None, "tenant-a"],   # principal
             "tenant-a"),
            ([0, 5, "whoami", [], {}, 30.0, "tenant-b"],     # all slots
             "tenant-b"),
        ]
        for env, expect in cases:
            msg = _roundtrip(srv.port, msgpack.packb(env))
            assert msg[0] == 1 and msg[1] == env[1]
            assert msg[2] is None, f"error for {env}: {msg[2]}"
            assert msg[3] == expect, env
    finally:
        srv.stop()


@pytest.mark.parametrize("native", [False, True])
def test_malformed_principal_degrades_not_errors(native):
    """A garbage 7th element bills as untagged/garbage — the dispatch
    itself must still succeed (a bad tag is not a bad request)."""
    if native and not native_server.available():
        pytest.skip("native transport unavailable")
    srv = _whoami_server(native)
    try:
        for seventh in (42, [], {}, b"\xff\xfebytes", ""):
            env = [0, 9, "whoami", [], None, None, seventh]
            msg = _roundtrip(srv.port, msgpack.packb(env))
            assert msg[0] == 1 and msg[2] is None, (seventh, msg)
            # non-string garbage degrades to no-principal; raw bytes
            # decode with replacement and still bill SOMEONE
            if not isinstance(seventh, (bytes, str)) or seventh == "":
                assert msg[3] == ""
    finally:
        srv.stop()


def test_old_peer_interop_untagged_client_stays_4_element():
    """A client with no principal (and no trace/deadline) must emit the
    byte-identical pre-ISSUE-19 4-element frame — old peers never see a
    shape they don't know. With a principal set, the envelope grows to
    exactly 7 with nil-padded trace/deadline slots."""
    seen = []
    lsock = socket.socket()
    lsock.bind(("127.0.0.1", 0))
    lsock.listen(4)
    port = lsock.getsockname()[1]
    import threading

    def serve():
        while True:
            try:
                conn, _ = lsock.accept()
            except OSError:
                return
            unp = msgpack.Unpacker(raw=False)
            data = conn.recv(65536)
            unp.feed(data)
            for msg in unp:
                seen.append(msg)
                conn.sendall(msgpack.packb([1, msg[1], None, "ok"]))
            conn.close()

    t = threading.Thread(target=serve, daemon=True)
    t.start()
    try:
        with RpcClient("127.0.0.1", port, timeout=10) as c:
            assert c.call("ping") == "ok"
        with principals.use("acme"):
            with RpcClient("127.0.0.1", port, timeout=10) as c:
                assert c.call("ping") == "ok"
    finally:
        lsock.close()
    assert len(seen) == 2
    assert len(seen[0]) == 4, seen[0]
    assert len(seen[1]) == 7, seen[1]
    assert seen[1][4] is None and seen[1][5] is None
    assert seen[1][6] == "acme"


def test_cpp_relay_forwards_principal_verbatim():
    """The C++ relay forwards the whole 7-element frame verbatim: the
    BACKEND's dispatch sees the tenant, with zero relay-side decode."""
    if not native_server.available():
        pytest.skip("native transport unavailable")
    back = native_server.NativeRpcServer()
    back.register("probe",
                  lambda n: principals.current() or "", arity=1)
    bport = back.serve_background(0, host="127.0.0.1")
    front = native_server.NativeRpcServer()
    front.register("probe", lambda n: "(python)", arity=1)
    front.serve_background(0, host="127.0.0.1")
    try:
        assert front.relay_config(
            ["probe"], {"c": [("127.0.0.1", bport)]}, timeout=5.0)
        env = [0, 11, "probe", ["c"], None, None, "relayed-tenant"]
        msg = _roundtrip(front.port, msgpack.packb(env))
        assert msg[2] is None and msg[3] == "relayed-tenant", msg
    finally:
        front.stop()
        back.stop()


# -- the ledger end to end -----------------------------------------------------


def test_server_bills_tenants_and_serves_get_usage():
    """Tagged train/classify traffic lands in the per-tenant table; the
    untagged stream bills (untagged); get_usage serves the mergeable
    doc; the conservation identity (ledger CPU == span-plane CPU) holds
    on a live server."""
    from jubatus_tpu.client import ClassifierClient, Datum
    from jubatus_tpu.server import EngineServer
    from jubatus_tpu.utils import usage as usage_mod

    srv = EngineServer("classifier", CONF)
    port = srv.start(0)
    try:
        rows = [["a", Datum({"x": 1.0})]]
        with principals.use("checkout"):
            c = ClassifierClient("127.0.0.1", port, "")
            for _ in range(5):
                c.train(rows)
            c.classify([Datum({"x": 1.0})])
            c.close()
        c = ClassifierClient("127.0.0.1", port, "")
        c.train(rows)  # untagged stream
        c.close()

        doc = srv.usage.snapshot()
        table = doc["table"]
        assert "checkout" in table and "train" in table["checkout"]
        assert table["checkout"]["train"][0] >= 5   # requests
        assert "(untagged)" in table
        # bytes flow both ways on every billed request
        tot = srv.usage.totals()
        assert tot["bytes_in"] > 0 and tot["bytes_out"] > 0

        # conservation: the ledger's CPU books equal the span plane's
        hists = srv.rpc.trace.snapshot()["hists"]
        span_s = sum(h["total_s"] for n, h in hists.items()
                     if n.startswith("rpc.") and
                     not n.startswith("rpc.client."))
        assert tot["cpu_seconds"] == pytest.approx(span_s, rel=1e-6)

        # the RPC view is the same doc, keyed by node name
        with RpcClient("127.0.0.1", port, timeout=10) as rc:
            served = rc.call("get_usage", "")
        (served_doc,) = served.values()
        assert "checkout" in served_doc["table"]

        # fold two node docs: cells SUM, capacity sums — never averages
        fleet = usage_mod.merge_usage([doc, served_doc])
        folded = {p: agg for p, agg in usage_mod.principal_rows(fleet)}
        assert folded["checkout"]["requests"] >= \
            2 * table["checkout"]["train"][0]
    finally:
        srv.stop()


def test_get_status_carries_usage_rows():
    """jubactl -c watch's tenant column reads usage.* rows straight off
    get_status."""
    from jubatus_tpu.client import ClassifierClient, Datum
    from jubatus_tpu.server import EngineServer

    srv = EngineServer("classifier", CONF)
    port = srv.start(0)
    try:
        with principals.use("ads"):
            c = ClassifierClient("127.0.0.1", port, "")
            c.train([["a", Datum({"x": 1.0})]])
            c.close()
        srv.usage.tick(0.0)
        with RpcClient("127.0.0.1", port, timeout=10) as rc:
            st = next(iter(rc.call("get_status", "").values()))
        assert st["usage.principals"] >= 1
        assert st["usage.top_principal"]
    finally:
        srv.stop()
