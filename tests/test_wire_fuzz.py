"""Wire-robustness fuzz: garbage, truncated frames, and adversarial
msgpack on raw sockets must never take a server down — the next valid
client call still answers. Run against BOTH transports.
"""

from __future__ import annotations

import socket

import pytest

from jubatus_tpu.rpc import native_server
from jubatus_tpu.rpc.client import RpcClient
from jubatus_tpu.rpc.server import RpcServer

GARBAGE = [
    b"\xff\xff\xff\xff",                      # invalid type bytes
    b"\x94",                                   # truncated request envelope
    b"\xdc\xff\xff",                           # array16 huge count, no body
    b"\x94\x00\x01\xa3abc",                    # request missing params
    b"\x91\x00",                               # 1-element array (bad envelope)
    b"\xc1" * 64,                              # reserved bytes
    bytes(range(256)),                         # everything
    b"\x94\x02\x01\xa1m\x90",                  # response-typed on server
]


def _servers():
    out = []
    py = RpcServer()
    py.register("ping", lambda: "pong", arity=0)
    py.serve_background(0, host="127.0.0.1")
    out.append(("python", py))
    if native_server.available():
        nat = native_server.NativeRpcServer()
        nat.register("ping", lambda: "pong", arity=0)
        nat.serve_background(0, host="127.0.0.1")
        out.append(("native", nat))
    return out


@pytest.fixture(scope="module")
def servers():
    ss = _servers()
    yield ss
    for _, s in ss:
        s.stop()


def test_garbage_never_kills_server(servers):
    for name, srv in servers:
        for blob in GARBAGE:
            s = socket.create_connection(("127.0.0.1", srv.port), timeout=5)
            try:
                s.sendall(blob)
                s.settimeout(0.3)
                try:
                    s.recv(4096)  # server may close or stay silent — both fine
                except (socket.timeout, OSError):
                    pass
            finally:
                s.close()
        # after all garbage, a clean client still gets service
        with RpcClient("127.0.0.1", srv.port, timeout=5.0) as c:
            assert c.call("ping") == "pong", f"{name} transport died"


def test_partial_frame_then_completion(servers):
    """A request split across many tiny writes must still be answered."""
    import msgpack

    payload = msgpack.packb([0, 7, "ping", []])
    for name, srv in servers:
        s = socket.create_connection(("127.0.0.1", srv.port), timeout=5)
        try:
            for i in range(len(payload)):
                s.sendall(payload[i:i + 1])
            s.settimeout(10)
            unp = msgpack.Unpacker(raw=False)
            got = None
            while got is None:
                chunk = s.recv(4096)
                assert chunk, f"{name}: connection closed mid-response"
                unp.feed(chunk)
                for msg in unp:
                    got = msg
                    break
            assert got[0] == 1 and got[1] == 7 and got[3] == "pong", name
        finally:
            s.close()


def test_oversized_method_name(servers):
    import msgpack

    for name, srv in servers:
        with RpcClient("127.0.0.1", srv.port, timeout=5.0) as c:
            from jubatus_tpu.rpc.errors import RpcMethodNotFound

            with pytest.raises(RpcMethodNotFound):
                c.call("m" * 10000)
            assert c.call("ping") == "pong", name


def test_proxy_raw_relay_survives_garbage_and_recovers():
    """The proxy's zero-decode relay path (round 3) faces client bytes
    before any generic validation: garbage, truncated frames, and
    odd-shaped params must never kill the proxy or the backend, and a
    valid call must still route afterwards."""
    import random as _random

    import msgpack

    from jubatus_tpu.client import ClassifierClient, Datum
    from jubatus_tpu.coord.memory import MemoryCoordinator, _Store
    from jubatus_tpu.server import EngineServer
    from jubatus_tpu.server.args import ServerArgs
    from jubatus_tpu.server.proxy import Proxy, ProxyArgs

    store = _Store()
    conf = {"method": "PA", "parameter": {"regularization_weight": 1.0},
            "converter": {"num_rules": [{"key": "*", "type": "num"}]}}
    srv = EngineServer("classifier", conf,
                       args=ServerArgs(engine="classifier",
                                       coordinator="(shared)", name="fz",
                                       listen_addr="127.0.0.1",
                                       interval_sec=1e9,
                                       interval_count=1 << 30),
                       coord=MemoryCoordinator(store))
    srv.start(0)
    proxy = Proxy(ProxyArgs(engine="classifier", listen_addr="127.0.0.1"),
                  coord=MemoryCoordinator(store))
    pport = proxy.start(0)
    try:
        # garbage frames straight at the proxy
        for g in GARBAGE:
            s = socket.create_connection(("127.0.0.1", pport), timeout=5)
            try:
                s.sendall(g)
                s.settimeout(0.3)
                try:
                    s.recv(4096)
                except (socket.timeout, OSError):
                    pass  # close/RST or silence — both acceptable
            finally:
                s.close()
        # odd but well-formed params through the relay: wrong name type,
        # empty params, non-array params, mutated train bytes
        base = msgpack.packb(
            [0, 1, "train",
             ["fz", [["a", Datum({"x": 1.0}).to_msgpack()]]]],
            use_bin_type=True)
        rng = _random.Random(5)
        odd = [
            msgpack.packb([0, 1, "train", [7, []]]),
            msgpack.packb([0, 1, "train", []]),
            msgpack.packb([0, 1, "train", "notarray"]),
            msgpack.packb([0, 1, "classify", ["fz", "x"]]),
        ]
        for _ in range(60):
            raw = bytearray(base)
            for _ in range(rng.randint(1, 5)):
                raw[rng.randrange(len(raw))] = rng.randrange(256)
            odd.append(bytes(raw))
        for payload in odd:
            s = socket.create_connection(("127.0.0.1", pport), timeout=5)
            try:
                s.sendall(payload)
                s.settimeout(0.5)
                try:
                    s.recv(4096)  # error reply, silence, or reset — all ok
                except (socket.timeout, OSError):
                    pass
            finally:
                s.close()
        # the tier still works end to end
        with ClassifierClient("127.0.0.1", pport, "fz",
                              timeout=10.0) as c:
            assert c.train([["pos", Datum({"a": 1.0})],
                            ["neg", Datum({"b": 1.0})]]) == 2
            (r,) = c.classify([Datum({"a": 1.0})])
            assert dict(r)["pos"] > dict(r)["neg"]
    finally:
        proxy.stop()
        srv.stop()
