"""Wire-robustness fuzz: garbage, truncated frames, and adversarial
msgpack on raw sockets must never take a server down — the next valid
client call still answers. Run against BOTH transports.
"""

from __future__ import annotations

import socket

import pytest

from jubatus_tpu.rpc import native_server
from jubatus_tpu.rpc.client import RpcClient
from jubatus_tpu.rpc.server import RpcServer

GARBAGE = [
    b"\xff\xff\xff\xff",                      # invalid type bytes
    b"\x94",                                   # truncated request envelope
    b"\xdc\xff\xff",                           # array16 huge count, no body
    b"\x94\x00\x01\xa3abc",                    # request missing params
    b"\x91\x00",                               # 1-element array (bad envelope)
    b"\xc1" * 64,                              # reserved bytes
    bytes(range(256)),                         # everything
    b"\x94\x02\x01\xa1m\x90",                  # response-typed on server
]


def _servers():
    out = []
    py = RpcServer()
    py.register("ping", lambda: "pong", arity=0)
    py.serve_background(0, host="127.0.0.1")
    out.append(("python", py))
    if native_server.available():
        nat = native_server.NativeRpcServer()
        nat.register("ping", lambda: "pong", arity=0)
        nat.serve_background(0, host="127.0.0.1")
        out.append(("native", nat))
    return out


@pytest.fixture(scope="module")
def servers():
    ss = _servers()
    yield ss
    for _, s in ss:
        s.stop()


def test_garbage_never_kills_server(servers):
    for name, srv in servers:
        for blob in GARBAGE:
            s = socket.create_connection(("127.0.0.1", srv.port), timeout=5)
            try:
                s.sendall(blob)
                s.settimeout(0.3)
                try:
                    s.recv(4096)  # server may close or stay silent — both fine
                except (socket.timeout, OSError):
                    pass
            finally:
                s.close()
        # after all garbage, a clean client still gets service
        with RpcClient("127.0.0.1", srv.port, timeout=5.0) as c:
            assert c.call("ping") == "pong", f"{name} transport died"


def test_partial_frame_then_completion(servers):
    """A request split across many tiny writes must still be answered."""
    import msgpack

    payload = msgpack.packb([0, 7, "ping", []])
    for name, srv in servers:
        s = socket.create_connection(("127.0.0.1", srv.port), timeout=5)
        try:
            for i in range(len(payload)):
                s.sendall(payload[i:i + 1])
            s.settimeout(10)
            unp = msgpack.Unpacker(raw=False)
            got = None
            while got is None:
                chunk = s.recv(4096)
                assert chunk, f"{name}: connection closed mid-response"
                unp.feed(chunk)
                for msg in unp:
                    got = msg
                    break
            assert got[0] == 1 and got[1] == 7 and got[3] == "pong", name
        finally:
            s.close()


def test_oversized_method_name(servers):
    import msgpack

    for name, srv in servers:
        with RpcClient("127.0.0.1", srv.port, timeout=5.0) as c:
            from jubatus_tpu.rpc.errors import RpcMethodNotFound

            with pytest.raises(RpcMethodNotFound):
                c.call("m" * 10000)
            assert c.call("ping") == "pong", name
