"""ZooKeeper backend (coord/zk.py) — VERDICT r1 missing item 1.

Protocol-level tests run against tests/fake_zk.py (an in-process server
speaking the same jute wire) so the client's encoding, watch re-arm, and
session semantics are proven without a quorum. When ``JUBATUS_TPU_ZK``
points at a live ensemble (e.g. "127.0.0.1:2181"), the same contract
suite runs against the real thing — the reference's --enable-zktest
gating (wscript:138-139)."""

from __future__ import annotations

import os
import time
import uuid

import pytest

from jubatus_tpu.coord import create_coordinator
from jubatus_tpu.coord.zk import ZkCoordinator

from fake_zk import FakeZkServer  # tests/ is the rootdir on sys.path


def _backends():
    out = ["fake"]
    if os.environ.get("JUBATUS_TPU_ZK"):
        out.append("real")
    return out


@pytest.fixture(params=_backends())
def zk(request):
    """(make_coordinator, root_path) for a fake or real ensemble."""
    if request.param == "fake":
        srv = FakeZkServer()
        port = srv.start(0)
        root = "/jubatus_test"

        def make():
            return ZkCoordinator.from_locator(f"zk://127.0.0.1:{port}")

        yield make, root
        srv.stop()
    else:
        spec = os.environ["JUBATUS_TPU_ZK"]
        root = f"/jubatus_test_{uuid.uuid4().hex[:8]}"

        def make():
            return create_coordinator(f"zk://{spec}")

        yield make, root


def test_crud_roundtrip(zk):
    make, root = zk
    c = make()
    try:
        assert c.create(f"{root}/config/classifier/c1", b'{"m": 1}')
        assert not c.create(f"{root}/config/classifier/c1", b"dup")
        assert c.read(f"{root}/config/classifier/c1") == b'{"m": 1}'
        assert c.set(f"{root}/config/classifier/c1", b"v2")
        assert c.read(f"{root}/config/classifier/c1") == b"v2"
        assert c.set(f"{root}/config/classifier/new", b"x")  # set creates
        assert c.exists(f"{root}/config/classifier/new")
        assert sorted(c.list(f"{root}/config/classifier")) == ["c1", "new"]
        assert c.remove(f"{root}/config/classifier/new")
        assert not c.remove(f"{root}/config/classifier/new")
        assert c.read(f"{root}/config/classifier/nope") is None
    finally:
        c.close()


def test_ephemerals_die_with_session(zk):
    make, root = zk
    a = make()
    b = make()
    try:
        assert a.create(f"{root}/eph/nodes/h_1", b"", ephemeral=True)
        assert b.exists(f"{root}/eph/nodes/h_1")
        a.close()
        deadline = time.time() + 15
        while time.time() < deadline and b.exists(f"{root}/eph/nodes/h_1"):
            time.sleep(0.2)
        assert not b.exists(f"{root}/eph/nodes/h_1")
    finally:
        b.close()


def test_sequence_nodes_unique_and_ordered(zk):
    make, root = zk
    c = make()
    try:
        first = c.create_seq(f"{root}/seq/lock-", b"")
        second = c.create_seq(f"{root}/seq/lock-", b"")
        assert first != second and first < second
        assert first.startswith(f"{root}/seq/lock-")
    finally:
        c.close()


def test_watch_children_fires_and_rearms(zk):
    make, root = zk
    c = make()
    obs = make()
    try:
        fired = []
        obs.watch_children(f"{root}/wc/nodes", lambda p: fired.append(p))
        c.create(f"{root}/wc/nodes/a", b"", ephemeral=True)
        deadline = time.time() + 10
        while time.time() < deadline and len(fired) < 1:
            time.sleep(0.1)
        assert len(fired) >= 1
        # one-shot ZK watches must be re-armed by the client: a SECOND
        # change must also fire
        c.create(f"{root}/wc/nodes/b", b"", ephemeral=True)
        deadline = time.time() + 10
        while time.time() < deadline and len(fired) < 2:
            time.sleep(0.1)
        assert len(fired) >= 2
    finally:
        c.close()
        obs.close()


def test_watch_delete_fires(zk):
    make, root = zk
    c = make()
    obs = make()
    try:
        c.create(f"{root}/wd/me", b"")
        fired = []
        obs.watch_delete(f"{root}/wd/me", lambda p: fired.append(p))
        c.remove(f"{root}/wd/me")
        deadline = time.time() + 10
        while time.time() < deadline and not fired:
            time.sleep(0.1)
        assert fired == [f"{root}/wd/me"]
    finally:
        c.close()
        obs.close()


def test_locks_are_session_scoped(zk):
    make, root = zk
    a = make()
    b = make()
    try:
        assert a.try_lock(f"{root}/lk/master_lock")
        assert a.try_lock(f"{root}/lk/master_lock")  # reentrant for holder
        assert not b.try_lock(f"{root}/lk/master_lock")
        assert not b.unlock(f"{root}/lk/master_lock")
        assert a.unlock(f"{root}/lk/master_lock")
        assert b.try_lock(f"{root}/lk/master_lock")
        b.unlock(f"{root}/lk/master_lock")
        # session death releases the lock
        assert a.try_lock(f"{root}/lk/other")
        a.close()
        deadline = time.time() + 15
        got = False
        while time.time() < deadline:
            if b.try_lock(f"{root}/lk/other"):
                got = True
                break
            time.sleep(0.2)
        assert got, "lock not released by session death"
    finally:
        b.close()


def test_create_id_monotonic_across_sessions(zk):
    make, root = zk
    a = make()
    b = make()
    try:
        ids = [a.create_id(f"{root}/idg"), a.create_id(f"{root}/idg"),
               b.create_id(f"{root}/idg"), a.create_id(f"{root}/idg")]
        assert ids == sorted(ids)
        assert len(set(ids)) == len(ids)
    finally:
        a.close()
        b.close()


def test_engine_cluster_over_zk():
    """Full stack over the zk:// locator (fake ensemble): 2 classifiers
    register membership, train, mix, answer — the drop-in path an
    existing ZK deployment would use."""
    from jubatus_tpu.client import ClassifierClient, Datum
    from jubatus_tpu.server import EngineServer
    from jubatus_tpu.server.args import ServerArgs

    srv = FakeZkServer()
    port = srv.start(0)
    locator = f"zk://127.0.0.1:{port}"
    conf = {"method": "PA", "parameter": {"regularization_weight": 1.0},
            "converter": {"num_rules": [{"key": "*", "type": "num"}]}}
    servers = []
    try:
        for _ in range(2):
            args = ServerArgs(engine="classifier", coordinator=locator,
                              name="zc", listen_addr="127.0.0.1",
                              interval_sec=1e9, interval_count=1 << 30)
            s = EngineServer("classifier", conf, args)
            s.start(0)
            servers.append(s)
        c0 = ClassifierClient("127.0.0.1", servers[0].args.rpc_port, "zc")
        c1 = ClassifierClient("127.0.0.1", servers[1].args.rpc_port, "zc")
        for _ in range(4):
            c0.train([["pos", Datum({"a": 1.0})]])
            c1.train([["neg", Datum({"b": 1.0})]])
        assert len(c0.get_status()) == 1  # direct server status
        assert c0.do_mix() is True
        (r,) = c1.classify([Datum({"a": 1.0})])
        scores = dict(r)
        assert scores["pos"] > scores["neg"]
        c0.close()
        c1.close()
    finally:
        for s in servers:
            s.stop()
        srv.stop()


# -- in-session reconnect (VERDICT r2 missing item 1) ------------------------
# Fake-only: these need session_grace + expire_session + host-list surgery,
# which a shared real ensemble can't offer.


def _wait_until(cond, timeout=8.0, step=0.02):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if cond():
            return True
        time.sleep(step)
    return False


def test_socket_loss_resumes_session_ephemerals_survive():
    """A TCP reset is NOT session loss: the client reconnects with
    sessionId+passwd inside the negotiated timeout (zk.cpp:139-150), the
    ephemerals survive, and no delete/suicide watcher fires."""
    srv = FakeZkServer()
    srv.session_grace = 15.0
    port = srv.start(0)
    c = ZkCoordinator.from_locator(f"zk://127.0.0.1:{port}")
    try:
        assert c.create("/app/me", b"x", ephemeral=True)
        fired = []
        c.watch_delete("/app/me", fired.append)
        sid = c._conn.session_id

        c._conn._sock.shutdown(2)  # the network blip

        assert _wait_until(lambda: c._conn.reconnect_count == 1
                           and not c._conn._closed)
        assert c._conn.session_id == sid          # same session, new socket
        assert c.read("/app/me") == b"x"          # ephemeral survived
        assert c.exists("/app/me")
        time.sleep(0.3)
        assert fired == []                        # no spurious suicide
        # the session still works end to end
        assert c.create("/app/me2", b"y", ephemeral=True)
    finally:
        c.close()
        srv.stop()


def test_watches_rearm_after_reconnect():
    """One-shot watches die with the socket; after an in-session resume
    the coordinator re-arms them, so changes made through ANOTHER client
    still notify this one."""
    srv = FakeZkServer()
    srv.session_grace = 15.0
    port = srv.start(0)
    c = ZkCoordinator.from_locator(f"zk://127.0.0.1:{port}")
    other = ZkCoordinator.from_locator(f"zk://127.0.0.1:{port}")
    try:
        kids = []
        c.watch_children("/members", kids.append)
        c._conn._sock.shutdown(2)
        assert _wait_until(lambda: c._conn.reconnect_count == 1)
        seen = len(kids)
        other.create("/members/n1", b"")
        assert _wait_until(lambda: len(kids) > seen)
        assert c.list("/members") == ["n1"]
    finally:
        c.close()
        other.close()
        srv.stop()


def test_delete_during_disconnect_fires_on_resume():
    """A delete-watched node removed WHILE the socket is down can never
    deliver its event; the re-arm pass detects the absence and fires the
    handler on resume (no lost-deletion window)."""
    srv = FakeZkServer()
    srv.session_grace = 15.0
    port = srv.start(0)
    c = ZkCoordinator.from_locator(f"zk://127.0.0.1:{port}")
    other = ZkCoordinator.from_locator(f"zk://127.0.0.1:{port}")
    try:
        other.create("/app/gone", b"")
        fired = []
        c.watch_delete("/app/gone", fired.append)
        # force the reconnect loop to spin against a dead port while the
        # other client deletes the node
        real_hosts = c._conn.hosts
        c._conn.hosts = [("127.0.0.1", 1)]
        c._conn._sock.shutdown(2)
        assert _wait_until(lambda: not c._conn._up.is_set())
        other.remove("/app/gone")
        c._conn.hosts = real_hosts
        assert _wait_until(lambda: fired == ["/app/gone"])
        assert not c._conn._closed                # session itself survived
    finally:
        c.close()
        other.close()
        srv.stop()


def test_session_expiry_still_fires_session_lost():
    """Genuine server-side expiry during the outage must still take the
    suicide path: resume is answered with session 0, delete watchers
    fire, and the coordinator is dead."""
    srv = FakeZkServer()
    srv.session_grace = 15.0
    port = srv.start(0)
    c = ZkCoordinator.from_locator(f"zk://127.0.0.1:{port}")
    try:
        c.create("/app/me", b"", ephemeral=True)
        fired = []
        c.watch_delete("/app/me", fired.append)
        sid = c._conn.session_id
        # block reconnects while we expire the session server-side
        real_hosts = c._conn.hosts
        c._conn.hosts = [("127.0.0.1", 1)]
        c._conn._sock.shutdown(2)
        assert _wait_until(lambda: not c._conn._up.is_set())
        srv.expire_session(sid)
        c._conn.hosts = real_hosts
        assert _wait_until(lambda: fired == ["/app/me"], timeout=12.0)
        assert c._conn._closed
        with pytest.raises(Exception):
            c.read("/app/me")
    finally:
        c.close()
        srv.stop()


def test_reconnect_soak_randomized():
    """VERDICT r3 item 8: hundreds of randomized disconnect / delete-
    during-outage / watch-storm cycles against the fake quorum. Invariants
    after every cycle: the session survives (no suicide), the ephemeral
    registration is intact, every delete watcher fires EXACTLY once per
    actual delete (no loss, no double-fire), child watchers keep
    delivering, and neither side leaks watch entries."""
    import random

    srv = FakeZkServer()
    srv.session_grace = 60.0
    port = srv.start(0)
    c = ZkCoordinator.from_locator(f"zk://127.0.0.1:{port}")
    other = ZkCoordinator.from_locator(f"zk://127.0.0.1:{port}")
    rng = random.Random(0xA50C)
    fired: dict = {}          # path -> fire count
    deleted: dict = {}        # path -> expected fire count (1 per delete)
    kid_events = []
    try:
        assert c.create("/soak/me", b"alive", ephemeral=True)
        c.watch_children("/soak/kids", kid_events.append)
        seq = 0
        for cycle in range(250):
            action = rng.randrange(4)
            if action == 0:
                # network blip mid-session; must resume, not suicide
                before = c._conn.reconnect_count
                try:
                    c._conn._sock.shutdown(2)
                except OSError:
                    pass
                assert _wait_until(
                    lambda: c._conn.reconnect_count > before
                    and c._conn._up.is_set()), f"cycle {cycle}: no resume"
            elif action == 1:
                # delete-watched node removed while CONNECTED
                seq += 1
                p = f"/soak/d{seq}"
                other.create(p, b"")
                fired.setdefault(p, 0)
                c.watch_delete(p, lambda q: fired.__setitem__(
                    q, fired.get(q, 0) + 1))
                other.remove(p)
                deleted[p] = deleted.get(p, 0) + 1
            elif action == 2:
                # delete-watched node removed while DISCONNECTED: the
                # re-arm pass must detect the absence and fire exactly once
                seq += 1
                p = f"/soak/d{seq}"
                other.create(p, b"")
                fired.setdefault(p, 0)
                c.watch_delete(p, lambda q: fired.__setitem__(
                    q, fired.get(q, 0) + 1))
                real_hosts = c._conn.hosts
                c._conn.hosts = [("127.0.0.1", 1)]
                try:
                    c._conn._sock.shutdown(2)
                except OSError:
                    pass
                assert _wait_until(lambda: not c._conn._up.is_set())
                other.remove(p)
                deleted[p] = deleted.get(p, 0) + 1
                c._conn.hosts = real_hosts
                assert _wait_until(lambda: c._conn._up.is_set(),
                                   timeout=12.0), f"cycle {cycle}"
            else:
                # child watch storm
                seq += 1
                n = len(kid_events)
                other.create(f"/soak/kids/k{seq}", b"")
                assert _wait_until(lambda: len(kid_events) > n), \
                    f"cycle {cycle}: child watch went dead"
        # drain: every delete observed exactly once, nothing double-fired
        assert _wait_until(
            lambda: all(fired.get(p, 0) == n for p, n in deleted.items()),
            timeout=15.0), {p: (fired.get(p, 0), n)
                            for p, n in deleted.items()
                            if fired.get(p, 0) != n}
        assert all(n == 1 for n in deleted.values())
        # session alive the whole way; registration intact
        assert not c._conn._closed
        assert c.read("/soak/me") == b"alive"
        # no leaked client-side delete watchers (all popped on fire)
        assert not any(c._delete_watchers.get(p) for p in deleted)
        # server-side watch table bounded: only the live watch paths
        # (children + exists re-arms), not one entry per soak cycle
        with srv._lock if hasattr(srv, "_lock") else _null():
            leaked = sum(len(v) for v in srv._watches.values())
        assert leaked < 50, f"server watch table leaked: {leaked}"
    finally:
        c.close()
        other.close()
        srv.stop()


class _null:
    def __enter__(self):
        return self

    def __exit__(self, *a):
        return False


def test_lock_idgen_contention_soak():
    """VERDICT r4 item 8: multi-client zkmutex contention + id minting
    under connection churn against the fake quorum (≙ zk_test.cpp's
    trylock/create_id cases, here concurrent and chaotic).

    Four coordinators hammer one lock path and one id path from worker
    threads while the main thread blips random clients' connections
    (network outage, NOT session expiry — session_grace keeps ephemerals,
    so a blip must never silently release a held lock). Invariants:

      * mutual exclusion holds through every blip (no two workers inside
        the critical section; a surviving session keeps the lock node);
      * every client both acquires the lock and mints ids (liveness —
        contention and churn starve nobody out);
      * ids minted concurrently by all clients are globally unique and
        each client observes its own mints strictly increasing
        (create_id's version-counter contract,
        global_id_generator_zk.cpp:32-56)."""
    import random
    import threading

    srv = FakeZkServer()
    srv.session_grace = 60.0
    port = srv.start(0)
    n_clients = 4
    clients = [ZkCoordinator.from_locator(f"zk://127.0.0.1:{port}")
               for _ in range(n_clients)]
    stop = threading.Event()
    inside = [0]              # critical-section occupancy counter
    violations: list = []
    ids = [[] for _ in range(n_clients)]
    acquired = [0] * n_clients
    errors: list = []

    def worker(i: int) -> None:
        c = clients[i]
        rng = random.Random(0x1D6E + i)
        while not stop.is_set():
            try:
                ids[i].append(c.create_id("/soak/idgen"))
            except Exception:  # noqa: BLE001 — mint raced a blip; retry
                time.sleep(0.02)
            got = False
            try:
                got = c.try_lock("/soak/lock")
            except Exception:  # noqa: BLE001 — try_lock raced a blip
                time.sleep(0.02)
            if got:
                inside[0] += 1
                if inside[0] != 1:
                    violations.append((i, inside[0]))
                time.sleep(rng.uniform(0.0, 0.003))
                if inside[0] != 1:
                    violations.append((i, inside[0], "during"))
                inside[0] -= 1
                deadline = time.time() + 15.0
                while time.time() < deadline:
                    try:
                        if c.unlock("/soak/lock"):
                            break
                    except Exception:  # noqa: BLE001 — mid-reconnect
                        pass
                    time.sleep(0.05)
                else:
                    errors.append(f"client {i}: unlock never succeeded")
                    stop.set()
                acquired[i] += 1
            time.sleep(rng.uniform(0.0, 0.002))

    threads = [threading.Thread(target=worker, args=(i,), daemon=True)
               for i in range(n_clients)]
    try:
        for t in threads:
            t.start()
        # chaos plane: ~10 blips across random clients over ~7 s; each
        # must resume its session (reconnect_count advances, _up returns)
        rng = random.Random(0xC4A0)
        for blip in range(10):
            time.sleep(0.6)
            c = clients[rng.randrange(n_clients)]
            before = c._conn.reconnect_count
            try:
                c._conn._sock.shutdown(2)
            except OSError:
                pass
            assert _wait_until(
                lambda: c._conn.reconnect_count > before
                and c._conn._up.is_set(), timeout=12.0), \
                f"blip {blip}: client never resumed"
        time.sleep(1.0)
        stop.set()
        for t in threads:
            t.join(timeout=30.0)
        assert not any(t.is_alive() for t in threads), "worker hung"
        assert not errors, errors
        assert not violations, f"mutual exclusion broken: {violations[:5]}"
        assert all(a > 0 for a in acquired), \
            f"a client was starved of the lock: {acquired}"
        assert all(len(x) > 0 for x in ids), "a client minted no ids"
        flat = [v for lst in ids for v in lst]
        assert len(set(flat)) == len(flat), "duplicate ids minted"
        for i, lst in enumerate(ids):
            assert lst == sorted(lst), f"client {i} ids not increasing"
        # the winning sessions never expired (suicide would close conns)
        assert not any(c._conn._closed for c in clients)
    finally:
        stop.set()
        for c in clients:
            c.close()
        srv.stop()
