"""Measured numbers for the chip's claimed advantages (VERDICT r2 item 7):

  (1) multi-label L scaling — the CPU's per-example cost is linear in L
      (every label row is gathered for scores); the chip's packed [D, 2L]
      gather fetches all labels with one descriptor per feature, so the
      kernel is ~flat in L.
  (2) concurrent serving — the reference serializes every update under
      one write lock; added ingest threads buy lock contention. The chip
      answer is microbatching, whose e2e numbers bench_serving captures.
  (3) capacity — D=2^26 (1 GB f32 weights + 1 GB precision) via 2-way
      --shard-devices feature sharding.

CPU sides run anywhere; chip sides need the device (skipped with a note
when the tunnel is down). Results feed docs/PERF_NOTES.md's table.

Usage: PYTHONPATH=/root/repo[:/root/.axon_site] python tools/bench_chip_axes.py
       [--cpu-only]
"""

from __future__ import annotations

import ctypes
import json
import sys
import time

import numpy as np

D_BITS = 20
D = 1 << D_BITS
K = 64
N_CPU = 100000
BATCH = 32768
L_SWEEP = (2, 8, 32)
THREAD_SWEEP = (1, 4, 16)


def _lib():
    from jubatus_tpu import native as nb

    src = f"{nb.NATIVE_DIR}/arow_baseline.cpp"
    out = f"{nb.BUILD_DIR}/libarow_baseline.so"
    if nb._stale(src, out) and not nb._compile(src, out):
        raise RuntimeError("baseline compile failed")
    lib = ctypes.CDLL(out)
    ptr_i = np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS")
    ptr_f = np.ctypeslib.ndpointer(np.float32, flags="C_CONTIGUOUS")
    lib.jt_arow_baseline_multi.restype = ctypes.c_double
    lib.jt_arow_baseline_multi.argtypes = [
        ptr_i, ptr_f, ptr_i, ctypes.c_int, ctypes.c_int, ctypes.c_int64,
        ctypes.c_int, ctypes.c_float]
    lib.jt_arow_baseline_locked.restype = ctypes.c_double
    lib.jt_arow_baseline_locked.argtypes = [
        ptr_i, ptr_f, ptr_i, ctypes.c_int, ctypes.c_int, ctypes.c_int64,
        ctypes.c_int, ctypes.c_float, ctypes.c_int]
    return lib


def cpu_axes() -> dict:
    lib = _lib()
    rng = np.random.default_rng(0)
    idx = rng.integers(1, D, size=(N_CPU, K), dtype=np.int32)
    val = rng.normal(size=(N_CPU, K)).astype(np.float32)
    out = {}
    for L in L_SWEEP:
        labels = rng.integers(0, L, size=N_CPU).astype(np.int32)
        sps = lib.jt_arow_baseline_multi(idx, val, labels, N_CPU, K, D, L,
                                         1.0)
        out[f"cpu_L{L}_samples_per_sec"] = round(sps, 1)
    labels2 = rng.integers(0, 2, size=N_CPU).astype(np.int32)
    for t in THREAD_SWEEP:
        sps = lib.jt_arow_baseline_locked(idx, val, labels2, N_CPU, K, D, 2,
                                          1.0, t)
        out[f"cpu_locked_{t}threads_samples_per_sec"] = round(sps, 1)
    return out


def chip_l_sweep() -> dict:
    """ops.train_batch at L in L_SWEEP on the bench device (flat-in-L is
    the claim: the packed [D, 2L] layout gathers every label's values
    with one descriptor per feature).

    Keys are minted from the platform that actually ran: chip_L* only on
    a real accelerator; a CPU-fallback backend emits cpu_jax_L* plus a
    chip_l_error so no chip-named key can come from a CPU run
    (VERDICT r3)."""
    import jax
    import jax.numpy as jnp

    from jubatus_tpu.ops import classifier as C

    plat = jax.devices()[0].platform
    # chip_* only from the real chip (axon tunnel device); any other
    # backend records under its own platform name with an error note
    pfx = "chip" if plat in ("tpu", "axon") else \
        ("cpu_jax" if plat == "cpu" else f"{plat}_jax")
    rng = np.random.default_rng(0)
    out = {}
    if pfx != "chip":
        out["chip_l_error"] = (f"device backend is {plat} (not the chip); "
                               f"sweep recorded under {pfx}_L* keys")
    val = jnp.asarray(rng.normal(size=(BATCH, K)).astype(np.float32))
    idxs = [jnp.asarray(rng.integers(1, D, size=(BATCH, K), dtype=np.int32))
            for _ in range(5)]
    for L in L_SWEEP:
        labels = jnp.asarray(rng.integers(0, L, size=BATCH).astype(np.int32))
        mask = jnp.ones(L, dtype=bool)
        st = C.init_state(L, D, confidence=True)
        st = C.train_batch(st, idxs[0], val, labels, mask, 1.0,
                           method="AROW")
        float(jnp.sum(st.dw))
        t0 = time.perf_counter()
        for i in range(1, 5):
            st = C.train_batch(st, idxs[i], val, labels, mask, 1.0,
                               method="AROW")
        float(jnp.sum(st.dw))
        sps = 4 * BATCH / (time.perf_counter() - t0)
        out[f"{pfx}_L{L}_samples_per_sec"] = round(sps, 1)
        del st
    return out


def chip_shard_capacity() -> dict:
    """D=2^26 AROW (2 GB of state with covariance) via 2-way feature
    sharding — beyond one bench-host transfer budget; correctness +
    throughput on whatever devices exist (virtual CPU devices prove the
    sharding compiles; the real capacity point needs 2 chips)."""
    import jax

    n_dev = len(jax.devices())
    if n_dev < 2:
        return {"chip_shard_note": f"one visible device; --shard-devices "
                                   f"capacity point needs >=2 (have {n_dev})"}
    if jax.devices()[0].platform == "cpu":
        return {"chip_shard_note": "backend is cpu (virtual devices); "
                                   "capacity point needs real chips"}
    from jax.sharding import Mesh

    from jubatus_tpu.models.classifier import ClassifierDriver

    mesh = Mesh(jax.local_devices()[:2], axis_names=("shard",))
    d = ClassifierDriver(
        {"method": "AROW", "parameter": {"regularization_weight": 1.0},
         "converter": {"num_rules": [{"key": "*", "type": "num"}]}},
        dim_bits=26, mesh=mesh)
    rng = np.random.default_rng(0)
    b = 8192
    idx = rng.integers(1, 1 << 26, size=(b, K)).astype(np.int32)
    val = rng.normal(size=(b, K)).astype(np.float32)
    lidx = rng.integers(0, 2, size=b).astype(np.int32)
    d.train_indexed(["a", "b"], lidx, idx, val)
    jax.block_until_ready(d.state.w)
    t0 = time.perf_counter()
    for _ in range(3):
        d.train_indexed(["a", "b"], lidx, idx, val)
    jax.block_until_ready(d.state.w)
    sps = 3 * b / (time.perf_counter() - t0)
    return {"chip_shard2_d26_samples_per_sec": round(sps, 1)}


def main() -> None:
    try:
        out = cpu_axes()
    except (RuntimeError, OSError) as e:  # no toolchain: still print JSON
        out = {"cpu_axes_error": repr(e)[:160]}
    if "--cpu-only" not in sys.argv:
        try:
            out.update(chip_l_sweep())
        except Exception as e:  # noqa: BLE001
            out["chip_l_error"] = repr(e)[:160]
        try:
            out.update(chip_shard_capacity())
        except Exception as e:  # noqa: BLE001
            out["chip_shard_error"] = repr(e)[:160]
    print(json.dumps(out, indent=1))


if __name__ == "__main__":
    main()
