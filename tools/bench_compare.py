#!/usr/bin/env python3
"""Diff two BENCH_*.json rounds and gate key-metric regressions (ISSUE 8).

Every perf PR so far proved its win by hand-reading two JSON files; this
is the mechanical version — the perf trajectory's regression gate:

    python tools/bench_compare.py BENCH_r04.json BENCH_r05.json
    python tools/bench_compare.py --glob 'BENCH_r*.json'   # latest two
    python tools/bench_compare.py old.json new.json \
        --tolerance 0.05 --key-tolerance collective_round_ms_nproc4_d24=0.15

Inputs may be any of the repo's bench shapes: the round envelope
(``{"parsed": {"extra": {...}}}``), the full capture
(``{"extra": {...}, "value": ...}``), or a flat ``{key: number}`` dict
(bench_serving/profile_flush output) — numeric keys are flattened out of
all of them.

Regression direction is inferred per key:

- **higher is better** — throughput (``*_per_sec``, ``*samples_per_sec``),
  ``*_speedup``, engagement ``*_fraction``s;
- **lower is better** — latencies (``*_ms``/``*_ms_*``), overhead/cost
  ``*_ratio``s, ``*_wire_mb*``, ``*drift*``, error/timeout counts;
- **boolean gates** — ``*_ok`` / ``*_target`` flipping true→false is a
  regression regardless of tolerance;
- keys matching neither pattern are reported informationally and never
  gate (a new key or a removed key is also information, not a failure).

A key regresses when it moves beyond its tolerance (default
``--tolerance 0.05`` = 5%, overridable per key) in the bad direction.
Exit status: 0 clean, 1 regressions found, 2 usage/input error.
"""

from __future__ import annotations

import argparse
import glob as globlib
import json
import os
import re
import sys
from typing import Any, Dict, List, Optional, Tuple

DEFAULT_TOLERANCE = 0.05

#: key patterns whose larger values are better (checked before _LOWER:
#: a wire REDUCTION factor beats the _per_host substring it contains).
#: ``_capacity_per_replica`` covers the autoscaling plane (ISSUE 12):
#: steady-state examples/s each serving replica absorbs — shrinkage
#: means the fleet needs more replicas for the same traffic.
#: ``_quarantined`` covers the model-integrity plane (ISSUE 15): the
#: poison drill arms a known poisoner, so quarantined counts falling
#: means the guard stopped catching it — a regression exactly like a
#: throughput drop (its companion drift/recovery keys are down-good
#: via the _LOWER patterns).
#: ``_recall_at_`` covers the ANN tier (ISSUE 16): recall@k of the IVF
#: approximate top-k against the exact scan — any fall means the index
#: started returning wrong neighbors, the one regression an ANN tier
#: must never trade for speed. Its build throughput rides the existing
#: ``_per_sec`` pattern (``ann_build_rows_per_sec``).
#: ``_accuracy`` / ``_recall`` cover the data-quality plane (ISSUE 17):
#: prequential accuracy and shadow recall — model quality going DOWN is
#: the regression the whole plane exists to catch.
#: ``_headroom`` covers the usage-attribution plane (ISSUE 19):
#: ``capacity.headroom`` (spare capacity after per-tenant demand) —
#: shrinking headroom at the same offered load means the replica got
#: more expensive to run.
_HIGHER = re.compile(
    r"(_per_sec($|_)|samples_per_sec|_speedup($|_)|_fraction($|_)"
    r"|_reduction($|_)|_capacity_per_replica($|_)|_quarantined($|_)"
    r"|_recall_at_|_accuracy($|_)|_recall($|_)|_headroom($|_))")
#: key patterns whose smaller values are better. ``_per_host`` covers
#: the hierarchical-mix scaling plane (ISSUE 9): wire bytes each host
#: ships per round — the quantity the two-tier reduce holds down, so
#: growth is a regression exactly like a latency
#: ``rows_lost`` covers the elastic-membership plane (ISSUE 10): rows
#: missing after a join/migrate/drain cycle — any growth is data loss.
#: ``_stall_ms`` / ``_lag_rounds`` cover the async mix plane (ISSUE
#: 11): model-lock stall on the serving path and rounds-behind-master
#: — both down-good (`_stall_ms` already matches `_ms`, listed for the
#: record; `_lag_rounds` needs its own pattern)
#: ``_recovery_s`` / ``_violation_s`` cover the autoscaling plane
#: (ISSUE 12): flash-onset-to-recovered wall time and seconds spent in
#: SLO violation — growth in either means the control loop got slower
#: at absorbing a traffic step.
#: ``_us`` covers the event plane (ISSUE 14): per-emit microseconds
#: (``e2e_event_emit_us``) — a hot-path cost, down-good like any
#: latency.
#: ``_drift_score`` / ``_psi`` cover the data-quality plane (ISSUE 17):
#: PSI drift between reference and live windows — on an unshifted
#: stream any growth means a false drift alarm (the bare ``drift``
#: pattern already matches ``_drift_score``; ``_psi`` needs its own).
#: ``_coldstart_to_serving_s`` / ``_model_loss_rows`` cover the durable
#: model plane (ISSUE 18): fleet wall time from first boot to first
#: served answer, and rows the killall drill lost BEYOND the diff-chain
#: tail — growth in the former means recovery got slower, any growth in
#: the latter is durability loss (the contract is zero). The warm-boot
#: wall time rides the existing ``_recovery_s`` pattern
#: (``e2e_warmboot_recovery_s``) and the warm-beats-cold verdict rides
#: ``_ok`` (``e2e_warmboot_beats_cold_ok``).
#: ``_err_frac`` covers the usage-attribution plane (ISSUE 19): the
#: conservation gap between the ledger's accounted CPU/device time and
#: the span plane's process totals
#: (``e2e_usage_attribution_err_frac``) — growth means requests are
#: escaping attribution. The overhead verdicts ride the existing
#: ``_ratio`` pattern (``e2e_usage_overhead_mean_ratio``).
#: ``_converge_rounds`` covers the self-tuning plane (ISSUE 20): mix
#: rounds the perf tuner burned before landing within the regret band
#: of the hand-tuned optimum (``e2e_tune_converge_rounds``) — growth
#: means the search got slower; the regret itself rides ``_ratio``
#: (``e2e_tune_regret_ratio``).
_LOWER = re.compile(
    r"(_ms($|_)|_ratio($|_)|_us($|_)|wire_mb|_per_host($|_)|drift"
    r"|_error(s)?($|_)|_timeouts|_errors_total|_denials|rows_lost"
    r"|_stall_ms($|_)|_lag_rounds($|_)"
    r"|_recovery_s($|_)|_violation_s($|_)|_psi($|_)"
    r"|_coldstart_to_serving_s($|_)|_model_loss_rows($|_)"
    r"|_err_frac($|_)|_converge_rounds($|_))")

#: built-in per-key tolerance defaults (explicit --key-tolerance wins):
#: the nproc16 sweep time-slices 16 gloo processes over however few
#: cores the box has, so its WALL times swing far beyond the 5% default
#: on pure scheduler noise — its wire-byte keys are arithmetic and keep
#: the tight gate
_DEFAULT_KEY_TOL: List[Tuple[re.Pattern, float]] = [
    (re.compile(r"_ms_nproc16($|_)"), 0.30),
    # churn-window quantiles ride kill/boot timing on a shared core:
    # the GATES of record are the error fractions and rows_lost (tight);
    # the churn latency/throughput keys get a loose band
    (re.compile(r"_churn_(p99_inflation_ratio|rpc_.*_ms"
                r"|mixed_samples_per_sec)"), 0.50),
]


def default_tolerance_for(key: str, fallback: float) -> float:
    for pat, tol in _DEFAULT_KEY_TOL:
        if pat.search(key):
            return tol
    return fallback
#: boolean gates: True -> False is a regression
_BOOL_GATE = re.compile(r"(_ok($|_)|_target($|_))")


def flatten(doc: Any, prefix: str = "") -> Dict[str, Any]:
    """Numeric/bool leaves of a bench JSON, flattened. The round
    envelope's ``parsed``/``extra`` nesting collapses WITHOUT a prefix —
    ``extra.e2e_x`` and a flat ``e2e_x`` must compare as the same key
    across bench shapes."""
    out: Dict[str, Any] = {}
    if isinstance(doc, dict):
        for k, v in doc.items():
            k = str(k)
            if k in ("parsed", "extra"):
                out.update(flatten(v, prefix))
            elif isinstance(v, dict):
                out.update(flatten(v, f"{prefix}{k}."))
            elif isinstance(v, bool) or isinstance(v, (int, float)):
                out[f"{prefix}{k}"] = v
    return out


def direction(key: str) -> Optional[str]:
    """'higher' | 'lower' | 'bool' | None (ungated)."""
    if _BOOL_GATE.search(key):
        return "bool"
    if _HIGHER.search(key):
        return "higher"
    if _LOWER.search(key):
        return "lower"
    return None


def compare(old: Dict[str, Any], new: Dict[str, Any],
            tolerance: float = DEFAULT_TOLERANCE,
            key_tolerance: Optional[Dict[str, float]] = None
            ) -> Tuple[List[Dict[str, Any]], List[Dict[str, Any]]]:
    """Diff two flat metric maps; returns (rows, regressions). Each row:
    {key, old, new, change, direction, verdict} — verdict in
    {"ok", "improved", "REGRESSED", "info", "added", "removed"}."""
    key_tolerance = key_tolerance or {}
    rows: List[Dict[str, Any]] = []
    regressions: List[Dict[str, Any]] = []
    for key in sorted(set(old) | set(new)):
        o, n = old.get(key), new.get(key)
        if o is None or n is None:
            rows.append({"key": key, "old": o, "new": n, "change": None,
                         "direction": direction(key),
                         "verdict": "added" if o is None else "removed"})
            continue
        d = direction(key)
        tol = key_tolerance.get(key)
        if tol is None:
            tol = default_tolerance_for(key, tolerance)
        if not isinstance(o, (bool, int, float)) \
                or not isinstance(n, (bool, int, float)):
            # defensive: callers may pass unflattened maps with string
            # leaves — those are information, never a gate
            rows.append({"key": key, "old": o, "new": n, "change": None,
                         "direction": None, "verdict": "info"})
            continue
        if d == "bool" or isinstance(o, bool) or isinstance(n, bool):
            verdict = "ok"
            if bool(o) and not bool(n):
                verdict = "REGRESSED"
            elif not bool(o) and bool(n):
                verdict = "improved"
            row = {"key": key, "old": bool(o), "new": bool(n),
                   "change": None, "direction": "bool", "verdict": verdict}
        else:
            o, n = float(o), float(n)
            change = (n - o) / abs(o) if o else (0.0 if n == o else None)
            verdict = "info"
            if change is None and d in ("higher", "lower"):
                # zero baseline, nonzero now: relative change is
                # unbounded, which is the OPPOSITE of ungateable — a
                # loss counter (rows_lost, _model_loss_rows) whose
                # contract is exactly zero must trip on ANY growth
                grew = n > o
                verdict = "REGRESSED" if grew == (d == "lower") \
                    else "improved"
            elif d == "higher":
                verdict = "REGRESSED" if (change is not None
                                          and change < -tol) else \
                    ("improved" if change is not None and change > tol
                     else "ok")
            elif d == "lower":
                verdict = "REGRESSED" if (change is not None
                                          and change > tol) else \
                    ("improved" if change is not None and change < -tol
                     else "ok")
            row = {"key": key, "old": o, "new": n, "change": change,
                   "direction": d, "verdict": verdict}
        rows.append(row)
        if row["verdict"] == "REGRESSED":
            regressions.append(row)
    return rows, regressions


def load_metrics(path: str) -> Dict[str, Any]:
    with open(path) as f:
        return flatten(json.load(f))


def pick_latest_two(pattern: str) -> Tuple[str, str]:
    """(older, newer) by name sort — the repo's rounds are numbered
    (BENCH_r01..), so lexical order IS chronological order; ties or
    exotic names fall back to mtime."""
    paths = sorted(globlib.glob(pattern))
    if len(paths) < 2:
        raise ValueError(
            f"--glob {pattern!r} matched {len(paths)} file(s); need >= 2")
    paths.sort(key=lambda p: (os.path.basename(p), os.path.getmtime(p)))
    return paths[-2], paths[-1]


def _fmt(v: Any) -> str:
    if isinstance(v, bool):
        return str(v)
    if isinstance(v, float):
        return f"{v:.4g}"
    return str(v)


def render(rows: List[Dict[str, Any]], old_path: str, new_path: str,
           show_all: bool = False) -> str:
    lines = [f"bench_compare: {old_path} -> {new_path}"]
    shown = 0
    for r in rows:
        if not show_all and r["verdict"] in ("ok", "added", "removed",
                                             "info"):
            continue
        shown += 1
        chg = (f"{r['change'] * 100:+.1f}%" if isinstance(r["change"], float)
               else "-")
        lines.append(f"  {r['verdict']:<10} {r['key']:<52} "
                     f"{_fmt(r['old']):>12} -> {_fmt(r['new']):>12}  {chg}")
    gated = sum(1 for r in rows if r["direction"] is not None
                and r["verdict"] not in ("added", "removed"))
    regressed = sum(1 for r in rows if r["verdict"] == "REGRESSED")
    improved = sum(1 for r in rows if r["verdict"] == "improved")
    lines.append(f"  {gated} gated key(s): {regressed} regressed, "
                 f"{improved} improved, "
                 f"{gated - regressed - improved} within tolerance")
    if not shown and not show_all:
        lines.insert(1, "  (no keys moved beyond tolerance; --all to "
                     "list everything)")
    return "\n".join(lines)


def main(argv: Optional[List[str]] = None) -> int:
    p = argparse.ArgumentParser(
        prog="bench_compare",
        description="diff two BENCH_*.json rounds; exit 1 on key-metric "
                    "regressions beyond tolerance")
    p.add_argument("old", nargs="?", help="older round JSON")
    p.add_argument("new", nargs="?", help="newer round JSON")
    p.add_argument("--glob", dest="glob_pat", default="",
                   help="pick the latest two files matching this glob "
                        "instead of naming them (lexical order = round "
                        "order for BENCH_rNN names)")
    p.add_argument("--tolerance", type=float, default=DEFAULT_TOLERANCE,
                   help="relative change beyond which a gated key "
                        "regresses (default 0.05 = 5%%)")
    p.add_argument("--key-tolerance", action="append", default=[],
                   metavar="KEY=FRAC",
                   help="per-key tolerance override (repeatable), e.g. "
                        "collective_round_ms_nproc4_d24=0.15 for a "
                        "noisy key")
    p.add_argument("--all", action="store_true",
                   help="print every compared key, not just movers")
    ns = p.parse_args(argv)
    try:
        if ns.glob_pat:
            old_path, new_path = pick_latest_two(ns.glob_pat)
        elif ns.old and ns.new:
            old_path, new_path = ns.old, ns.new
        else:
            print("need OLD NEW paths or --glob", file=sys.stderr)
            return 2
        key_tol: Dict[str, float] = {}
        for spec in ns.key_tolerance:
            key, _, frac = spec.partition("=")
            if not key or not frac:
                print(f"bad --key-tolerance {spec!r} (want KEY=FRAC)",
                      file=sys.stderr)
                return 2
            key_tol[key] = float(frac)
        old = load_metrics(old_path)
        new = load_metrics(new_path)
    except (OSError, ValueError, json.JSONDecodeError) as e:
        print(f"bench_compare: {e}", file=sys.stderr)
        return 2
    rows, regressions = compare(old, new, tolerance=ns.tolerance,
                                key_tolerance=key_tol)
    print(render(rows, old_path, new_path, show_all=ns.all))
    return 1 if regressions else 0


if __name__ == "__main__":
    sys.exit(main())
