#!/usr/bin/env python3
"""Feature-extraction microbench: per-datum convert vs convert_batch.

Sweeps batch size x converter config and reports samples/s for both
pipelines plus the speedup, JSON to stdout — the host-side half of the
ISSUE 5 trajectory (bench_serving measures the e2e serving plane; this
isolates featurization so a regression is attributable).

    python tools/bench_fv_sweep.py [--batches 256,2048,16384] [--seconds 1.0]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

CONFIGS = {
    "numeric": {"num_rules": [{"key": "*", "type": "num"}]},
    "text_tf": {"string_rules": [
        {"key": "*", "type": "space", "sample_weight": "tf",
         "global_weight": "bin"}]},
    "text_idf": {"string_rules": [
        {"key": "*", "type": "space", "sample_weight": "tf",
         "global_weight": "idf"}]},
    "combo": {
        "num_rules": [{"key": "*", "type": "num"}],
        "combination_rules": [
            {"key_left": "*", "key_right": "*", "type": "mul"}]},
}

K = 32  # features per datum (bench_serving's shape)


def _make_data(workload: str, n: int, rng):
    from jubatus_tpu.core import Datum

    vocab = [f"w{i:03d}" for i in range(400)]
    out = []
    for _ in range(n):
        if workload.startswith("text"):
            words = rng.choice(len(vocab), size=K)
            out.append(Datum({"body": " ".join(vocab[w] for w in words)}))
        else:
            out.append(Datum({f"f{j}": float(v)
                              for j, v in enumerate(rng.normal(size=K))}))
    return out


def _time_loop(fn, seconds: float) -> float:
    """Calls/s of ``fn`` over a ~``seconds`` window (>= 1 call)."""
    fn()  # warm (memo caches, combo plans)
    n = 0
    t0 = time.perf_counter()
    deadline = t0 + seconds
    while True:
        fn()
        n += 1
        now = time.perf_counter()
        if now >= deadline:
            return n / (now - t0)


def run(batches, seconds: float, update_weights: bool = True) -> dict:
    from jubatus_tpu.core.fv import make_fv_converter

    rng = np.random.default_rng(0)
    out = {"k_features": K, "update_weights": update_weights}
    for name, conf in CONFIGS.items():
        wl = "text" if name.startswith("text") else "numeric"
        for b in batches:
            data = _make_data(wl, b, rng)
            per = make_fv_converter(conf, dim_bits=18)
            bat = make_fv_converter(conf, dim_bits=18)

            def run_per(per=per, data=data):
                for d in data:
                    per.convert(d, update_weights=update_weights)

            def run_bat(bat=bat, data=data):
                bat.convert_batch(data, update_weights=update_weights)

            sp = _time_loop(run_per, seconds) * b
            sb = _time_loop(run_bat, seconds) * b
            out[f"fv_per_datum_samples_per_sec_{name}_b{b}"] = round(sp, 1)
            out[f"fv_batch_samples_per_sec_{name}_b{b}"] = round(sb, 1)
            out[f"fv_batch_speedup_{name}_b{b}"] = round(sb / sp, 2) \
                if sp else 0.0
    return out


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--batches", default="256,2048,16384",
                    help="comma-separated batch sizes")
    ap.add_argument("--seconds", type=float, default=1.0,
                    help="measure window per cell")
    ap.add_argument("--no-update-weights", action="store_true",
                    help="bench the query-plane conversion (no observe)")
    args = ap.parse_args()
    batches = [int(x) for x in args.batches.split(",") if x]
    print(json.dumps(run(batches, args.seconds,
                         update_weights=not args.no_update_weights),
                     indent=1))


if __name__ == "__main__":
    main()
