"""Chunk-size sweep for the pipelined mix data plane.

Boots one 4-process jax.distributed CPU world per chunk size and times
``psum_pytree`` over a Criteo-shaped host diff (two [2, 2^23] f32 leaves
= 128 MB payload per replica) in EVERY wire mode — f32, bf16, and the
block-quantized int8 transport — printing a JSON dict of median round ms
per chunk size per mode. This is the recipe behind the DEFAULT_CHUNK_MB
choice recorded in docs/PERF_NOTES.md ("Mix data plane" / "Quantized
mix") — rerun it on a real chip to re-pick for ICI.

Usage: python tools/bench_mix_chunk_sweep.py [dim_bits] [sizes_mb...]
"""

from __future__ import annotations

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

_CHILD = r"""
import sys, time, json
import numpy as np
import jax
jax.config.update("jax_platforms", "cpu")
pid = int(sys.argv[1]); n = int(sys.argv[2])
jax_port = sys.argv[3]
dim_bits = int(sys.argv[5]); chunk_mb = float(sys.argv[6])
from jubatus_tpu.parallel.multihost import enable_cpu_collectives
enable_cpu_collectives()
jax.distributed.initialize(f"127.0.0.1:{jax_port}", num_processes=n,
                           process_id=pid)
from jubatus_tpu.parallel.collective import ErrorFeedback, psum_pytree

rng = np.random.default_rng(pid)
diff = {"dw": rng.normal(size=(2, 1 << dim_bits)).astype(np.float32),
        "dprec": rng.normal(size=(2, 1 << dim_bits)).astype(np.float32)}
rec = {"chunk_mb": chunk_mb}
ef = ErrorFeedback()
# every process runs the modes in the same order: the collective
# sequences stay in lockstep without any coordination protocol
for mode in ("off", "bf16", "int8"):
    kw = {"feedback": ef} if mode == "int8" else {}
    phases = {}
    psum_pytree(diff, compress=mode, phases=phases, chunk_mb=chunk_mb,
                **kw)  # warmup (compile)
    times = []
    for _ in range(3):
        t0 = time.perf_counter()
        phases = {}
        psum_pytree(diff, compress=mode, phases=phases,
                    chunk_mb=chunk_mb, **kw)
        times.append(time.perf_counter() - t0)
    tag = {"off": "f32", "bf16": "bf16", "int8": "int8"}[mode]
    rec[tag] = {
        "psum_ms_median": round(float(np.median(times)) * 1e3, 1),
        "chunks": phases.get("chunks"),
        "wire_mb": phases.get("wire_mb"),
        "overlap_ms_saved": phases.get("overlap_ms_saved"),
        "ship_ms": phases.get("ship_ms"),
        "reduce_ms": phases.get("reduce_ms"),
        "readback_ms": phases.get("readback_ms"),
    }
if pid == 0:
    print("SWEEP=" + json.dumps(rec), flush=True)
print(f"CHILD-{pid}-DONE", flush=True)
"""


def sweep(dim_bits: int = 23, sizes=(2.0, 4.0, 8.0, 16.0, 32.0, 4096.0)):
    """4096 MB = never chunk: the serial single-collective reference."""
    import bench_mix

    out = {}
    for mb in sizes:
        outs, rcs = bench_mix.run_jax_world(
            _CHILD, 4, timeout=900, extra_args=(str(dim_bits), str(mb)))
        if any(rc != 0 for rc in rcs):
            out[f"chunk_{mb}mb"] = {"error": (''.join(outs))[-200:]}
            continue
        for text in outs:
            for line in text.splitlines():
                if line.startswith("SWEEP="):
                    out[f"chunk_{mb}mb"] = json.loads(line[len("SWEEP="):])
    return out


if __name__ == "__main__":
    bits = int(sys.argv[1]) if len(sys.argv) > 1 else 23
    sizes = tuple(float(s) for s in sys.argv[2:]) or \
        (2.0, 4.0, 8.0, 16.0, 32.0, 4096.0)
    print(json.dumps(sweep(bits, sizes), indent=1))
