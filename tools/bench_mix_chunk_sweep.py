"""Chunk-size sweep for the pipelined mix data plane.

Boots one jax.distributed CPU world per chunk size and times
``psum_pytree`` over a Criteo-shaped host diff (two [2, 2^23] f32 leaves
= 128 MB payload per replica) in EVERY wire mode — f32, bf16, and the
block-quantized int8 transport — printing a JSON dict of median round ms
per chunk size per mode. This is the recipe behind the DEFAULT_CHUNK_MB
choice recorded in docs/PERF_NOTES.md ("Mix data plane" / "Quantized
mix") — rerun it on a real chip to re-pick for ICI.

``--topology NxM`` runs the sweep through the HIERARCHICAL two-tier
reduce (ISSUE 9) instead of the flat ring: the world grows to N*M
processes grouped N hosts x M co-located processes each, and every mode
reports the per-tier split (``intra_ms``/``inter_ms``) plus
``wire_bytes_per_host`` — re-picking the chunk size for the tiered
pipeline, whose inter-host ring ships 1/M of each chunk per lane.

Usage: python tools/bench_mix_chunk_sweep.py [dim_bits] [sizes_mb...]
                                             [--topology NxM]
"""

from __future__ import annotations

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

_CHILD = r"""
import sys, time, json
import numpy as np
import jax
jax.config.update("jax_platforms", "cpu")
pid = int(sys.argv[1]); n = int(sys.argv[2])
jax_port = sys.argv[3]
dim_bits = int(sys.argv[5]); chunk_mb = float(sys.argv[6])
topo = sys.argv[7] if len(sys.argv) > 7 else "flat"
from jubatus_tpu.parallel.multihost import enable_cpu_collectives
enable_cpu_collectives()
jax.distributed.initialize(f"127.0.0.1:{jax_port}", num_processes=n,
                           process_id=pid)
from jubatus_tpu.parallel.collective import ErrorFeedback, psum_pytree

rng = np.random.default_rng(pid)
diff = {"dw": rng.normal(size=(2, 1 << dim_bits)).astype(np.float32),
        "dprec": rng.normal(size=(2, 1 << dim_bits)).astype(np.float32)}
rec = {"chunk_mb": chunk_mb, "topo": topo}
hier = {} if topo == "flat" else {"topology": topo}
ef = ErrorFeedback()
# every process runs the modes in the same order: the collective
# sequences stay in lockstep without any coordination protocol
for mode in ("off", "bf16", "int8"):
    kw = dict(hier, **({"feedback": ef} if mode == "int8" else {}))
    phases = {}
    psum_pytree(diff, compress=mode, phases=phases, chunk_mb=chunk_mb,
                **kw)  # warmup (compile)
    times = []
    for _ in range(3):
        t0 = time.perf_counter()
        phases = {}
        psum_pytree(diff, compress=mode, phases=phases,
                    chunk_mb=chunk_mb, **kw)
        times.append(time.perf_counter() - t0)
    tag = {"off": "f32", "bf16": "bf16", "int8": "int8"}[mode]
    rec[tag] = {
        "psum_ms_median": round(float(np.median(times)) * 1e3, 1),
        "chunks": phases.get("chunks"),
        "wire_mb": phases.get("wire_mb"),
        "overlap_ms_saved": phases.get("overlap_ms_saved"),
        "ship_ms": phases.get("ship_ms"),
        "reduce_ms": phases.get("reduce_ms"),
        "readback_ms": phases.get("readback_ms"),
    }
    if topo != "flat":
        rec[tag]["intra_ms"] = phases.get("intra_ms")
        rec[tag]["inter_ms"] = phases.get("inter_ms")
        rec[tag]["wire_bytes_per_host"] = phases.get("wire_bytes_per_host")
if pid == 0:
    print("SWEEP=" + json.dumps(rec), flush=True)
print(f"CHILD-{pid}-DONE", flush=True)
"""


def sweep(dim_bits: int = 23, sizes=(2.0, 4.0, 8.0, 16.0, 32.0, 4096.0),
          topology: str = "flat"):
    """4096 MB = never chunk: the serial single-collective reference.
    ``topology`` != "flat" sizes the world to H*M processes and routes
    every round through the two-tier reduce."""
    import bench_mix

    if topology == "flat":
        n = 4
    else:
        h, _, m = topology.partition("x")
        n = int(h) * int(m)
    out = {}
    for mb in sizes:
        outs, rcs = bench_mix.run_jax_world(
            _CHILD, n, timeout=900,
            extra_args=(str(dim_bits), str(mb), topology))
        if any(rc != 0 for rc in rcs):
            out[f"chunk_{mb}mb"] = {"error": (''.join(outs))[-200:]}
            continue
        for text in outs:
            for line in text.splitlines():
                if line.startswith("SWEEP="):
                    out[f"chunk_{mb}mb"] = json.loads(line[len("SWEEP="):])
    return out


def _parse_argv(argv):
    topology = "flat"
    rest = []
    i = 0
    while i < len(argv):
        if argv[i] == "--topology":
            if i + 1 >= len(argv):
                raise SystemExit("--topology needs an NxM value")
            topology = argv[i + 1]
            i += 2
        elif argv[i].startswith("--topology="):
            topology = argv[i].split("=", 1)[1]
            i += 1
        else:
            rest.append(argv[i])
            i += 1
    bits = int(rest[0]) if rest else 23
    sizes = tuple(float(s) for s in rest[1:]) or \
        (2.0, 4.0, 8.0, 16.0, 32.0, 4096.0)
    return bits, sizes, topology


if __name__ == "__main__":
    bits, sizes, topology = _parse_argv(sys.argv[1:])
    print(json.dumps(sweep(bits, sizes, topology), indent=1))
