"""A/B: pre-scatter dedup via host-computed permutation (VERDICT r3 #5).

The d2^24 AROW step is scatter-bound (4 scatter-adds ~= 123 ms vs ~60 ms
everything else; docs/PERF_NOTES.md). Hypothesis under test: since the
[B, K] indices are known HOST-side at parse time (native/fast_ingest.cpp
owns the batch), the C++ side can compute — off the device's critical
path — a sort permutation + segment boundaries, letting the device
replace each scatter-add with
    reorder-gather (updates[perm]) -> segment_sum(sorted ids) ->
    scatter into the n_unique touched rows.

What host pre-compute CANNOT do: pre-sum duplicate indices across
examples — the update value is alpha_b * x[b, k] with alpha computed ON
DEVICE per example, so only the permutation (value-independent) ships.

Variants timed (same process, alternating trials, median — the only
methodology the tunnel's ~10% variance allows):
  A  plain scatter-add of [B*K] updates (the shipping kernel's shape)
  B  updates[perm] -> segment_sum(indices_are_sorted=True) -> scatter
     of n_unique rows (permutation/segments precomputed host-side, cost
     EXCLUDED — models the C++ overlap)

Usage: PYTHONPATH=/root/repo:/root/.axon_site python tools/bench_scatter_dedup.py
Prints one JSON dict; feed the verdict into docs/PERF_NOTES.md.
"""

from __future__ import annotations

import json
import time

import numpy as np

D_BITS = 24
B = 32768
K = 64
TRIALS = 5


def main() -> None:
    import jax
    import jax.numpy as jnp

    d = 1 << D_BITS
    rng = np.random.default_rng(0)
    idx_host = rng.integers(1, d, size=B * K, dtype=np.int32)
    # host-side precompute (the part C++ would overlap with device work)
    t0 = time.perf_counter()
    perm = np.argsort(idx_host, kind="stable")
    sorted_idx = idx_host[perm]
    uniq, seg_start = np.unique(sorted_idx, return_index=True)
    seg_ids = np.zeros(B * K, np.int32)
    seg_ids[seg_start[1:]] = 1
    seg_ids = np.cumsum(seg_ids, dtype=np.int32)
    host_ms = (time.perf_counter() - t0) * 1e3
    n_uniq = len(uniq)

    table = jnp.zeros((d,), jnp.float32)
    upd = jnp.asarray(rng.normal(size=B * K).astype(np.float32))
    idx = jnp.asarray(idx_host)
    j_perm = jnp.asarray(perm.astype(np.int32))
    j_seg = jnp.asarray(seg_ids)
    j_uniq = jnp.asarray(uniq.astype(np.int32))

    @jax.jit
    def plain(tab, u):
        return tab.at[idx].add(u)

    @jax.jit
    def dedup(tab, u):
        s = jax.ops.segment_sum(u[j_perm], j_seg, num_segments=n_uniq,
                                indices_are_sorted=True)
        return tab.at[j_uniq].add(s, unique_indices=True,
                                  indices_are_sorted=True)

    # parity first
    a = plain(table, upd)
    b = dedup(table, upd)
    np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=2e-5,
                               atol=1e-5)

    out = {"platform": jax.devices()[0].platform, "n_unique": int(n_uniq),
           "dup_fraction": round(1.0 - n_uniq / (B * K), 4),
           "host_precompute_ms": round(host_ms, 1)}
    for name, fn in (("plain_scatter", plain), ("dedup_scatter", dedup)):
        fn(table, upd)
        float(jnp.sum(fn(table, upd)))  # warm + barrier
        times = []
        for _ in range(TRIALS):
            t0 = time.perf_counter()
            r = fn(table, upd)
            float(jnp.sum(r))
            times.append(time.perf_counter() - t0)
        out[f"{name}_ms"] = round(float(np.median(times)) * 1e3, 2)
    out["speedup"] = round(out["plain_scatter_ms"] /
                           out["dedup_scatter_ms"], 3)
    print(json.dumps(out, indent=1))


if __name__ == "__main__":
    main()
