#!/usr/bin/env python3
"""Metric-catalog gate (ISSUE 7): every exported metric key must be
documented in docs/OBSERVABILITY.md.

The observability plane is only as good as its catalog — an undocumented
counter is a dashboard nobody builds and an alert nobody writes. This
checker extracts every LITERAL counter/gauge key registered through the
tracing registry (``<...>.count("...")`` / ``<...>.gauge("...")`` /
``self._count("...")`` call sites across ``jubatus_tpu/``), normalizes
f-string placeholders (``{method}`` → ``*``), and requires each key to
match a catalog token in OBSERVABILITY.md (backtick-quoted, with
``<placeholder>`` segments as wildcards and ``{a,b}`` brace sets
expanded).

Keys built from variables (e.g. the breaker board's configurable
counter prefix) are invisible to a static scan and are documented by
hand; the gate covers the literal majority and every new ``slo.*`` /
``mix.*`` key.

Run directly or via the codestyle suite:

    python tools/check_metrics_docs.py          # rc 1 + listing if missing
"""

from __future__ import annotations

import glob
import itertools
import os
import re
import sys
from typing import Dict, List, Set, Tuple

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
DOC = os.path.join(REPO, "docs", "OBSERVABILITY.md")

#: registry call sites whose first argument is a literal metric key.
#: Receivers are constrained (trace/tracing/registry/…) so string
#: methods like ``line.count("x")`` never match.
_CALL_RE = re.compile(
    r"(?:\btrace|\btracing|\bregistry|\b_registry|\breg)\s*\.\s*"
    r"(?:count|gauge)\(\s*(f?)\"([^\"]+)\"")
_COUNT_HELPER_RE = re.compile(r"self\._count\(\s*(f?)\"([^\"]+)\"")

#: a plausible metric key after normalization: dotted lowercase segments
_KEY_RE = re.compile(r"^[a-z][a-z0-9_*]*(\.[a-z0-9_*]+)+$")

#: doc catalog tokens: anything backtick-quoted
_DOC_TOKEN_RE = re.compile(r"`([^`]+)`")


def _normalize_source_key(raw: str, is_fstring: bool) -> str:
    key = raw
    if is_fstring:
        key = re.sub(r"\{[^}]*\}", "*", key)
    return key


def _expand_doc_token(token: str) -> List[str]:
    """``zk.session.{lost,reconnects}`` → both keys; ``rpc.<method>.errors``
    → ``rpc.*.errors``."""
    token = re.sub(r"<[^>]+>", "*", token.strip())
    sets = re.findall(r"\{([^}]*)\}", token)
    if not sets:
        return [token]
    template = re.sub(r"\{[^}]*\}", "\x00", token)
    combos = itertools.product(*[s.split(",") for s in sets])
    out = []
    for combo in combos:
        t = template
        for part in combo:
            t = t.replace("\x00", part.strip(), 1)
        out.append(t)
    return out


def scan_source_keys(root: str = "") -> Dict[str, List[str]]:
    """Literal metric keys -> list of 'file:line' sites."""
    root = root or os.path.join(REPO, "jubatus_tpu")
    found: Dict[str, List[str]] = {}
    for path in sorted(glob.glob(os.path.join(root, "**", "*.py"),
                                 recursive=True)):
        rel = os.path.relpath(path, REPO)
        with open(path, encoding="utf-8") as f:
            for lineno, line in enumerate(f, 1):
                for pat in (_CALL_RE, _COUNT_HELPER_RE):
                    for m in pat.finditer(line):
                        key = _normalize_source_key(m.group(2),
                                                    m.group(1) == "f")
                        if _KEY_RE.match(key):
                            found.setdefault(key, []).append(
                                f"{rel}:{lineno}")
    return found


def doc_keys(doc_path: str = DOC) -> Set[str]:
    with open(doc_path, encoding="utf-8") as f:
        text = f.read()
    keys: Set[str] = set()
    for token in _DOC_TOKEN_RE.findall(text):
        for expanded in _expand_doc_token(token):
            if _KEY_RE.match(expanded):
                keys.add(expanded)
    return keys


def _segments_match(found: str, doc: str) -> bool:
    fs, ds = found.split("."), doc.split(".")
    if len(fs) != len(ds):
        return False
    return all(f == d or f == "*" or d == "*" for f, d in zip(fs, ds))


def missing_keys(found: Dict[str, List[str]],
                 documented: Set[str]) -> List[Tuple[str, List[str]]]:
    out = []
    for key in sorted(found):
        if not any(_segments_match(key, d) for d in documented):
            out.append((key, found[key]))
    return out


def main(argv: List[str] | None = None) -> int:
    args = sys.argv[1:] if argv is None else argv
    root = args[0] if args else ""
    found = scan_source_keys(root)
    documented = doc_keys()
    missing = missing_keys(found, documented)
    for key, sites in missing:
        print(f"UNDOCUMENTED metric key {key!r} "
              f"(exported at {', '.join(sites[:3])}) — add it to the "
              "metric catalog in docs/OBSERVABILITY.md")
    print(f"{len(missing)} undocumented of {len(found)} exported "
          f"metric key(s); {len(documented)} catalog token(s)")
    return 1 if missing else 0


if __name__ == "__main__":
    sys.exit(main())
