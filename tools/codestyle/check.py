#!/usr/bin/env python3
"""Mechanical style gate (≙ tools/codestyle/run_cpplint.sh + pre-commit).

Self-contained (no lint packages in the image): enforces the rules that
never need judgment — UTF-8, LF endings, no tabs in Python, no trailing
whitespace, newline at EOF, and a module docstring on every package
module. Run directly or via the test suite:

    python tools/codestyle/check.py [paths...]
"""

from __future__ import annotations

import ast
import glob
import os
import re
import sys
from typing import List

MAX_LINE = 110  # hard mechanical ceiling; idiomatic target is ~79

#: directories whose modules sit on (or next to) request/mix hot paths:
#: raw ``time.time()`` there is almost always a latency-measurement bug
#: (non-monotonic under NTP slew — use time.perf_counter/monotonic or a
#: tracing span). Genuine wall-clock timestamps (status maps, checkpoint
#: headers) opt out per line with a ``# wall-clock`` pragma.
HOT_TIME_DIRS = (
    "jubatus_tpu/rpc/",
    "jubatus_tpu/parallel/",
    "jubatus_tpu/native/",
    "jubatus_tpu/server/",
    "jubatus_tpu/framework/",
)

#: request-plane directories where a bare ``except Exception`` (or a
#: naked ``except:``) around RPC work silently flattens the typed error
#: taxonomy (rpc/errors.py) — retryable-vs-fatal, breaker evidence, and
#: deadline classification all die inside it. Catch the taxonomy
#: (RpcError subclasses / is_retryable) instead; the rare genuinely-broad
#: catch (teardown, never-raise-into-C++ shims, handler invocation
#: boundaries) opts out per line with a ``# broad-ok`` pragma stating why.
BROAD_EXCEPT_DIRS = (
    "jubatus_tpu/rpc/",
    "jubatus_tpu/server/",
    "jubatus_tpu/framework/",
)


#: collective hot-path directories where a HOST-side numpy dtype cast
#: (``.astype(np.*)`` / ``.astype(ml_dtypes.*)``) stages a full copy of
#: the payload on the host before the wire ever sees it — the exact bug
#: the quantized transport killed (ISSUE 6: the bf16 path's host astype
#: cost ~740 ms per d24 round; ``collective_phase_cast_ms_d24_bf16``).
#: Cast/quantize ON DEVICE instead: a jnp dtype inside the jitted
#: ship/reduce path (collective._cast_fn / _quant_chunk_fn). The rare
#: genuine host cast (tiny metadata arrays, pre-staging for a host-only
#: code path) opts out per line with a ``# host-cast-ok`` pragma
#: stating why.
HOST_CAST_DIRS = (
    "jubatus_tpu/parallel/",
)

_HOST_CAST_RE = re.compile(r"\.astype\(\s*(np|numpy|ml_dtypes)\.")


#: sharded-layout hot paths where a FULL-MATRIX device→host gather
#: (``jax.device_get`` / ``multihost_utils.process_allgather``) undoes
#: the entire point of feature/row sharding (ISSUE 13): the weight
#: matrix lives distributed precisely so no single buffer ever holds
#: it — one stray gather reintroduces the HBM/host-RAM cliff the
#: sharded layout removed AND serializes every shard through one copy.
#: Ship per-shard chunks instead (sharded_model.shard_chunks), or read
#: back only reduced/replicated results (scores, top-k candidates).
#: The rare legitimate full readback (a replicated mix total, a debug
#: dump) opts out per line with a ``# full-gather-ok`` pragma stating
#: why.
FULL_GATHER_DIRS = (
    "jubatus_tpu/parallel/",
    "jubatus_tpu/models/",
)

_FULL_GATHER_RE = re.compile(
    r"\bjax\s*\.\s*device_get\(|\bdevice_get\(|\bprocess_allgather\(")


#: ANN query-path gate (ISSUE 16): the IVF tier's entire reason to
#: exist is that a query touches only the probed cells' rows — a call
#: to any ARENA-WIDE scorer (the batch distance kernels that sweep
#: every row, or the sharded full scan) inside an ``ivf`` module
#: silently reintroduces the exact-scan cliff the tier removed while
#: still reporting "approximate" latencies. Score gathered candidates
#: with ops/ivf.py's candidate_* kernels instead. The rare legitimate
#: full sweep (a recall-probe shadow query, a rebuild pass) opts out
#: per line with a ``# full-scan-ok`` pragma stating why.
_FULL_SCAN_RE = re.compile(
    r"\b(_?hamming_distances_batch(_xla)?|_?minhash_distances_batch(_xla)?"
    r"|euclid_lsh_distances_batch|cosine_scores|euclid_distances"
    r"|sharded_distances)\s*\(")


def _is_ann_query_path(posix_path: str) -> bool:
    return ("/jubatus_tpu/" in posix_path
            and "ivf" in os.path.basename(posix_path))


#: serving hot-path directories where a per-datum ``converter.convert()``
#: call INSIDE a loop/comprehension is the featurization cliff the batch
#: pipeline exists to remove (ISSUE 5: ~29x between per-datum convert and
#: batch-native featurization at the bench shape) — use
#: ``converter.convert_batch`` and slice rows instead. Genuine per-datum
#: sites (single-datum APIs re-converting one row) opt out per line with
#: a ``# per-datum-ok`` pragma stating why.
CONVERT_LOOP_DIRS = (
    "jubatus_tpu/server/",
    "jubatus_tpu/models/",
)


def _check_convert_loops(path: str, tree: "ast.AST",
                         lines: List[str]) -> List[str]:
    """Flag ``<...>.converter.convert(...)`` (or ``converter.convert``)
    calls nested inside a for/while loop or comprehension."""
    problems = []
    loop_nodes = (ast.For, ast.AsyncFor, ast.While, ast.ListComp,
                  ast.SetComp, ast.DictComp, ast.GeneratorExp)

    def is_convert_call(node) -> bool:
        if not (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr == "convert"):
            return False
        obj = node.func.value
        return (isinstance(obj, ast.Name) and obj.id == "converter") or \
            (isinstance(obj, ast.Attribute) and obj.attr == "converter")

    for outer in ast.walk(tree):
        if not isinstance(outer, loop_nodes):
            continue
        for node in ast.walk(outer):
            if node is outer or not is_convert_call(node):
                continue
            line = lines[node.lineno - 1] if node.lineno <= len(lines) else ""
            if "# per-datum-ok" in line:
                continue
            problems.append(
                f"{path}:{node.lineno}: per-datum converter.convert() in a "
                "loop on a serving hot path (use converter.convert_batch "
                "and CSRBatch rows — the batch pipeline; append "
                "'# per-datum-ok — <why>' where a single-datum call is "
                "genuinely required)")
    return problems


#: event-coverage gate (ISSUE 14): the audited state-transition sites —
#: a breaker outcome folding into its state machine, a drain phase set,
#: an SLO firing edge, an autoscaler journal write — must emit a typed
#: event into the cluster event plane (utils/events.py), or the
#: `jubactl -c timeline` narrative silently loses that subsystem. The
#: marker regex matches the transition line; the ENCLOSING FUNCTION must
#: contain an ``events.emit(`` / ``.events.emit(`` / ``self._emit(``
#: call. A transition genuinely surfaced elsewhere opts out per line
#: with a ``# no-event`` pragma stating where.
EVENT_SITES = (
    ("jubatus_tpu/rpc/breaker.py",
     re.compile(r"record_(failure|success)\(\)"),
     "breaker state transition"),
    ("jubatus_tpu/framework/migration.py",
     re.compile(r"self\.state\s*="),
     "drain phase transition"),
    ("jubatus_tpu/utils/slo.py",
     re.compile(r"st\[\"firing\"\]\s*="),
     "SLO firing transition"),
    ("jubatus_tpu/coord/controller.py",
     re.compile(r"self\.journal\.append\("),
     "controller decision/actuation record"),
)

_EMIT_RE = re.compile(r"(\bevents\.emit\(|\.events\.emit\(|self\._emit\()")


#: tuner-knob gate (ISSUE 20): the perf tuner actuates the wire chunk
#: size, wire mode, coalescer depth, and mix cadence at runtime — a
#: HARD-CODED numeric for one of those knobs inside an actuated module
#: is a second source of truth the tuner silently fights (the knob
#: snaps back, or two code paths disagree about the plan). The single
#: home for knob defaults is coord/perf_tuner.TUNER_DEFAULTS plus the
#: operator flags in server/args.py; everything else must READ the
#: live attribute. A genuinely static constant (a floor, an EWMA
#: smoothing factor, a compatibility default that predates the tuner)
#: opts out per line with a ``# knob-ok`` pragma stating why.
TUNED_KNOB_FILES = (
    "jubatus_tpu/framework/collective_mixer.py",
    "jubatus_tpu/framework/mixer.py",
    "jubatus_tpu/framework/async_mixer.py",
    "jubatus_tpu/server/microbatch.py",
    "jubatus_tpu/parallel/collective.py",
)

_KNOB_RE = re.compile(
    r"\b(chunk_mb|chunk_bytes|max_batch|interval_sec|flush_interval_ms"
    r"|premix_interval)\s*[=:]\s*[-+]?[0-9.]", re.IGNORECASE)


#: model-integrity coverage gate (ISSUE 15): mix is model averaging —
#: one admitted NaN/norm-exploded contribution poisons every peer's
#: weights in a single round. So every FOLD site (``tree_sum(...)``)
#: and APPLY site (``<...>.put_diff(...)``) in the mixer modules
#: (``framework/*mixer*.py``) must sit in a function that routes
#: through the admission guard (framework/model_guard.py) — a
#: ``guard`` reference in the enclosing function is the evidence. A
#: site that is genuinely pre-screened elsewhere (a broadcast of an
#: already-screened fold, a member's own two deltas merging) opts out
#: per line with a ``# no-guard`` pragma stating where the screen IS.
_GUARD_SITE_RE = re.compile(r"(\btree_sum\(|\.put_diff\()")
_GUARD_REF_RE = re.compile(r"(\bguard\b|_guard\b)")


def _is_guard_gated(posix_path: str) -> bool:
    return ("jubatus_tpu/framework/" in posix_path
            and "mixer" in os.path.basename(posix_path))


def _check_guard_coverage(path: str, tree: "ast.AST",
                          lines: List[str]) -> List[str]:
    """tree_sum/put_diff call sites in mixer modules must sit inside a
    function referencing the admission guard (or carry ``# no-guard``)."""
    funcs: List[tuple] = []
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            funcs.append((node.lineno, node.end_lineno))
    problems = []
    for i, line in enumerate(lines, 1):
        if not _GUARD_SITE_RE.search(line) or "# no-guard" in line:
            continue
        spans = [f for f in funcs if f[0] <= i <= f[1]]
        if spans:
            start, end = max(spans, key=lambda f: f[0])  # innermost
            body = "\n".join(lines[start - 1:end])
        else:
            body = line
        if not _GUARD_REF_RE.search(body):
            problems.append(
                f"{path}:{i}: mix fold/apply site without a model-guard "
                "reference in the enclosing function (screen the "
                "payloads through framework/model_guard.MixGuard before "
                "they fold or apply — one admitted NaN poisons the whole "
                "fleet in a round; append '# no-guard — <where the "
                "screen is>' where the site is genuinely pre-screened)")
    return problems


#: durable-model-plane coverage gate (ISSUE 18): every blob the model
#: store writes outlives the process that wrote it — a snapshot written
#: WITHOUT the CRC envelope stamp (framework/save_load.pack_envelope)
#: is silent corruption waiting for the warm-boot that trusts it, and
#: the store's read side refuses unstamped bytes by contract. So every
#: backend write site (``.put(...)`` / ``.put_blob(...)``) in a
#: model-store module must sit in a function that shows envelope
#: evidence — a ``pack_envelope`` (stamping) or ``read_envelope``
#: (verify-before-write precondition) reference in the enclosing
#: function. A site whose bytes are genuinely stamped upstream opts out
#: per line with a ``# no-crc`` pragma stating where the stamp IS.
_STORE_WRITE_RE = re.compile(r"(\.put\(|\.put_blob\()")
_CRC_REF_RE = re.compile(r"(pack_envelope|read_envelope)")


def _is_store_gated(posix_path: str) -> bool:
    return ("/jubatus_tpu/" in posix_path
            and "model_store" in os.path.basename(posix_path))


def _check_store_crc_coverage(path: str, tree: "ast.AST",
                              lines: List[str]) -> List[str]:
    """put/put_blob call sites in model-store modules must sit inside a
    function referencing the CRC envelope (or carry ``# no-crc``)."""
    funcs: List[tuple] = []
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            funcs.append((node.lineno, node.end_lineno))
    problems = []
    for i, line in enumerate(lines, 1):
        if not _STORE_WRITE_RE.search(line) or "# no-crc" in line:
            continue
        if re.search(r"def\s+put(_blob)?\s*\(", line):
            continue  # the definition, not a write site
        spans = [f for f in funcs if f[0] <= i <= f[1]]
        if spans:
            start, end = max(spans, key=lambda f: f[0])  # innermost
            body = "\n".join(lines[start - 1:end])
        else:
            body = line
        if not _CRC_REF_RE.search(body):
            problems.append(
                f"{path}:{i}: store write site without a CRC-envelope "
                "reference in the enclosing function (stamp the blob "
                "with save_load.pack_envelope — or verify it with "
                "read_envelope — before it hits the backend; an "
                "unstamped snapshot is silent corruption for the warm-"
                "boot that trusts it; append '# no-crc — <where the "
                "stamp is>' where the bytes are genuinely stamped "
                "upstream)")
    return problems


#: data-quality coverage gate (ISSUE 17): a train path that bypasses
#: the quality recorder is invisible to the drift/prequential plane —
#: the model silently trains on a stream nobody is evaluating. So every
#: ``register("train", ...)`` / ``register_raw("train", ...)`` site in
#: ``jubatus_tpu/server/`` must sit in a function that routes through
#: the quality recorder (a ``quality`` reference in the enclosing
#: function is the evidence). A train path genuinely recorded elsewhere
#: opts out per line with ``# no-quality`` stating where.
_QUALITY_SITE_RE = re.compile(r"\.register(?:_raw)?\(\s*f?\"train\"")
_QUALITY_REF_RE = re.compile(r"quality")


def _check_quality_coverage(path: str, tree: "ast.AST",
                            lines: List[str]) -> List[str]:
    """train registration sites in server modules must sit inside a
    function referencing the quality recorder (or carry
    ``# no-quality``)."""
    funcs: List[tuple] = []
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            funcs.append((node.lineno, node.end_lineno))
    problems = []
    for i, line in enumerate(lines, 1):
        if not _QUALITY_SITE_RE.search(line) or "# no-quality" in line:
            continue
        spans = [f for f in funcs if f[0] <= i <= f[1]]
        if spans:
            start, end = max(spans, key=lambda f: f[0])  # innermost
            body = "\n".join(lines[start - 1:end])
        else:
            body = line
        if not _QUALITY_REF_RE.search(body):
            problems.append(
                f"{path}:{i}: train registration without a quality-"
                "recorder reference in the enclosing function (route the "
                "path through server.quality — utils/quality.py — so the "
                "drift/prequential plane sees this stream; append "
                "'# no-quality — <where it IS recorded>' where the path "
                "is genuinely recorded elsewhere)")
    return problems


#: usage-attribution coverage gate (ISSUE 19): a train/classify path
#: that bypasses the usage recorder serves tenants whose cost nobody
#: accounts — the capacity model under-reads demand and the
#: conservation gate drifts. So every ``register("train"|"classify",
#: ...)`` / ``register_raw(...)`` site in ``jubatus_tpu/server/`` must
#: sit in a function that routes through the usage recorder (a
#: ``usage`` reference in the enclosing function is the evidence). A
#: path genuinely billed elsewhere — e.g. covered by the dispatch-span
#: sink alone — opts out per line with ``# no-usage`` stating where.
_USAGE_SITE_RE = re.compile(
    r"\.register(?:_raw)?\(\s*f?\"(?:train|classify)\"")
_USAGE_REF_RE = re.compile(r"usage")


def _check_usage_coverage(path: str, tree: "ast.AST",
                          lines: List[str]) -> List[str]:
    """train/classify registration sites in server modules must sit
    inside a function referencing the usage recorder (or carry
    ``# no-usage``)."""
    funcs: List[tuple] = []
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            funcs.append((node.lineno, node.end_lineno))
    problems = []
    for i, line in enumerate(lines, 1):
        if not _USAGE_SITE_RE.search(line) or "# no-usage" in line:
            continue
        spans = [f for f in funcs if f[0] <= i <= f[1]]
        if spans:
            start, end = max(spans, key=lambda f: f[0])  # innermost
            body = "\n".join(lines[start - 1:end])
        else:
            body = line
        if not _USAGE_REF_RE.search(body):
            problems.append(
                f"{path}:{i}: train/classify registration without a "
                "usage-recorder reference in the enclosing function "
                "(bill the path through server.usage — utils/usage.py — "
                "so per-tenant cost and the capacity model see this "
                "stream; append '# no-usage — <where it IS billed>' "
                "where the path is genuinely billed elsewhere)")
    return problems


def _check_event_coverage(path: str, posix: str, tree: "ast.AST",
                          lines: List[str]) -> List[str]:
    """Marker lines from EVENT_SITES must sit inside a function whose
    source contains an event-emission call (or carry ``# no-event``)."""
    sites = [(pat, desc) for suffix, pat, desc in EVENT_SITES
             if posix.endswith(suffix)]
    if not sites:
        return []
    # map line -> innermost enclosing function's (start, end) span
    funcs: List[tuple] = []
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            funcs.append((node.lineno, node.end_lineno))
    problems = []
    for i, line in enumerate(lines, 1):
        for pat, desc in sites:
            if not pat.search(line) or "# no-event" in line:
                continue
            spans = [f for f in funcs if f[0] <= i <= f[1]]
            if spans:
                start, end = max(spans, key=lambda f: f[0])  # innermost
                body = "\n".join(lines[start - 1:end])
            else:
                body = line
            if not _EMIT_RE.search(body):
                problems.append(
                    f"{path}:{i}: {desc} without an events.emit call in "
                    "the enclosing function (the cluster event timeline "
                    "loses this transition — emit into the owning "
                    "registry's journal, or append '# no-event — <where "
                    "it IS surfaced>')")
    return problems


def _is_span_timed(posix_path: str) -> bool:
    """Files whose hot-path timing must go through the tracing registry's
    ``span()`` helper (ISSUE 4): RPC dispatch and the mixer round paths.
    A hand-rolled ``time.perf_counter()`` pair there produces a duration
    the forensics plane never sees — no histogram, no span store entry,
    no slow-log eligibility — so the measurement silently falls out of
    every operator view. The registry helper is the same two
    perf_counter calls PLUS the record. Genuinely raw timers (the span
    helper's own implementation, code that must not touch the registry
    lock) opt out per line with a ``# raw-timer`` pragma stating why."""
    if posix_path.endswith(("jubatus_tpu/rpc/server.py",
                            "jubatus_tpu/rpc/client.py",
                            "jubatus_tpu/rpc/native_server.py")):
        return True
    return ("jubatus_tpu/framework/" in posix_path
            and "mixer" in os.path.basename(posix_path))


def iter_files(roots: List[str]) -> List[str]:
    out = []
    for root in roots:
        if os.path.isfile(root):
            out.append(root)
            continue
        for p in glob.glob(os.path.join(root, "**", "*.py"), recursive=True):
            if "/build/" not in p:
                out.append(p)
    return sorted(out)


def check_file(path: str) -> List[str]:
    problems = []
    with open(path, "rb") as f:
        raw = f.read()
    try:
        text = raw.decode("utf-8")
    except UnicodeDecodeError as e:
        return [f"{path}: not valid UTF-8 ({e})"]
    if b"\r\n" in raw:
        problems.append(f"{path}: CRLF line endings")
    if raw and not raw.endswith(b"\n"):
        problems.append(f"{path}: no newline at end of file")
    # files embedding templates for tab-indented languages (Go) opt out
    # of the tab rule with this pragma in their first 10 lines
    allow_tabs = "codestyle: allow-tabs" in "\n".join(
        text.splitlines()[:10])
    posix = path.replace(os.sep, "/")
    hot_time = path.endswith(".py") and any(
        d in posix for d in HOT_TIME_DIRS)
    broad_gate = path.endswith(".py") and any(
        d in posix for d in BROAD_EXCEPT_DIRS)
    host_cast = path.endswith(".py") and any(
        d in posix for d in HOST_CAST_DIRS)
    full_gather = path.endswith(".py") and any(
        d in posix for d in FULL_GATHER_DIRS)
    ann_path = path.endswith(".py") and _is_ann_query_path(posix)
    span_timed = path.endswith(".py") and _is_span_timed(posix)
    knob_gate = path.endswith(".py") and any(
        posix.endswith(f) for f in TUNED_KNOB_FILES)
    for i, line in enumerate(text.splitlines(), 1):
        if "\t" in line and not allow_tabs:
            problems.append(f"{path}:{i}: tab character")
        if line != line.rstrip():
            problems.append(f"{path}:{i}: trailing whitespace")
        if len(line) > MAX_LINE:
            problems.append(f"{path}:{i}: line longer than {MAX_LINE} chars"
                            f" ({len(line)})")
        if host_cast and "# host-cast-ok" not in line and \
                _HOST_CAST_RE.search(line):
            problems.append(
                f"{path}:{i}: host-side numpy dtype cast in a collective "
                "hot path (a full host copy of the payload before the "
                "wire — cast/quantize on device inside the jitted "
                "ship/reduce path with a jnp dtype instead; append "
                "'# host-cast-ok — <why>' where a host cast is genuinely "
                "required)")
        if full_gather and "# full-gather-ok" not in line and \
                _FULL_GATHER_RE.search(line):
            problems.append(
                f"{path}:{i}: full-matrix device_get/allgather in a "
                "sharded-layout hot path (materializing a sharded leaf "
                "reintroduces the memory cliff the layout removed — ship "
                "per-shard chunks via sharded_model.shard_chunks or read "
                "back reduced results only; append '# full-gather-ok — "
                "<why>' where a full readback is genuinely required)")
        if ann_path and "# full-scan-ok" not in line and \
                _FULL_SCAN_RE.search(line):
            problems.append(
                f"{path}:{i}: arena-wide distance sweep in an ANN query "
                "path (scanning every row reintroduces the exact-scan "
                "cliff the IVF tier removed — rescore only the probed "
                "cells' gathered candidates via ops/ivf.py candidate_* "
                "kernels; append '# full-scan-ok — <why>' where a full "
                "sweep is genuinely required)")
        if knob_gate and "# knob-ok" not in line and \
                _KNOB_RE.search(line):
            problems.append(
                f"{path}:{i}: hard-coded tuner knob constant in an "
                "actuated module (the perf tuner owns this knob at "
                "runtime — a literal here is a second source of truth "
                "the tuner fights; put defaults in coord/perf_tuner."
                "TUNER_DEFAULTS or server/args.py and read the live "
                "attribute; append '# knob-ok — <why>' where a static "
                "constant is genuinely required)")
        if hot_time and "time.time()" in line and "# wall-clock" not in line:
            problems.append(
                f"{path}:{i}: raw time.time() in a hot-path module (use "
                "time.perf_counter/time.monotonic or a tracing span; "
                "append '# wall-clock' for genuine timestamps)")
        if span_timed and "time.perf_counter(" in line and \
                "# raw-timer" not in line:
            problems.append(
                f"{path}:{i}: hand-rolled perf_counter in an RPC-dispatch/"
                "mixer hot path (time it with the tracing registry's "
                "span() helper so the duration reaches the histograms, "
                "span store, and slow log; append '# raw-timer — <why>' "
                "where a raw timer is genuinely required)")
        stripped = line.strip()
        if broad_gate and "# broad-ok" not in line and (
                stripped.startswith("except Exception")
                or stripped == "except:"):
            problems.append(
                f"{path}:{i}: bare 'except Exception' in a request-plane "
                "module (catch the typed taxonomy from rpc/errors.py — "
                "RpcError subclasses, errors.is_retryable; append "
                "'# broad-ok — <why>' where a broad catch is genuinely "
                "required)")
    if path.endswith(".py") and "/jubatus_tpu/" in path.replace(os.sep, "/"):
        try:
            tree = ast.parse(text)
        except SyntaxError as e:
            return problems + [f"{path}: syntax error {e}"]
        if not os.path.basename(path) == "__main__.py" and \
                ast.get_docstring(tree) is None and text.strip():
            problems.append(f"{path}: missing module docstring")
        if any(d in posix for d in CONVERT_LOOP_DIRS):
            problems.extend(_check_convert_loops(path, tree,
                                                 text.splitlines()))
        problems.extend(_check_event_coverage(path, posix, tree,
                                              text.splitlines()))
        if "jubatus_tpu/server/" in posix:
            problems.extend(_check_quality_coverage(path, tree,
                                                    text.splitlines()))
            problems.extend(_check_usage_coverage(path, tree,
                                                  text.splitlines()))
        if _is_guard_gated(posix):
            problems.extend(_check_guard_coverage(path, tree,
                                                  text.splitlines()))
        if _is_store_gated(posix):
            problems.extend(_check_store_crc_coverage(path, tree,
                                                      text.splitlines()))
    return problems


def main(argv: List[str] | None = None) -> int:
    args = list(sys.argv[1:] if argv is None else argv)
    repo = os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))
    roots = args or [os.path.join(repo, "jubatus_tpu"),
                     os.path.join(repo, "tests"),
                     os.path.join(repo, "tools"),
                     os.path.join(repo, "docs")]
    files = iter_files(roots)
    problems = []
    for path in files:
        problems.extend(check_file(path))
    for p in problems:
        print(p)
    print(f"{len(problems)} problem(s) in {len(files)} files")
    return 1 if problems else 0


if __name__ == "__main__":
    sys.exit(main())
