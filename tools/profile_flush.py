#!/usr/bin/env python3
"""Profile the serving flush path: where does the per-flush time go?

Mimics service.py's train_raw flush at several batch sizes, separating:
  host   — _ensure_label loop + padding + slot array build
  disp   — jitted train_batch dispatch (async, no block)
  step   — device step time (dispatch..block_until_ready)
  pipe   — effective per-step time when N steps are dispatched back-to-back
           before one block (does the runtime pipeline them?)

ISSUE 8 rework: timing rides the tracing span plane (utils/tracing.py
Registry.span handles — the same histograms the servers export) instead
of hand-rolled wall-clock deltas, the optional ``--device-dir`` wraps
the measured loops in an XLA capture (utils/profiler.DeviceCapture's
machinery via tracing.device_trace), and ``--json`` emits a flat
``{key: number}`` map tools/bench_compare.py diffs against any other
round:

    python tools/profile_flush.py --json /tmp/flush_a.json
    ... change something ...
    python tools/profile_flush.py --json /tmp/flush_b.json
    python tools/bench_compare.py /tmp/flush_a.json /tmp/flush_b.json
"""
import argparse
import json
import sys

import numpy as np

import jax

from jubatus_tpu.models.classifier import ClassifierDriver
from jubatus_tpu.utils import tracing

CONF = {
    "method": "AROW",
    "parameter": {"regularization_weight": 1.0},
    "converter": {"num_rules": [{"key": "*", "type": "num"}]},
}
K = 32
REPS = 5
PIPE_DEPTH = 10
rng = np.random.default_rng(0)


def make_batch(b):
    labels = ["a" if x < 0.5 else "b" for x in rng.random(b)]
    idx = rng.integers(0, 1 << 18, size=(b, K)).astype(np.int32)
    val = rng.normal(size=(b, K)).astype(np.float32)
    return labels, idx, val


def profile_batch(d, reg, b):
    """One batch size's phase breakdown, measured as spans in ``reg``
    (span names carry the batch size so the registry's histograms — and
    the JSON — keep every shape separate)."""
    labels, idx, val = make_batch(b)
    # warm the compile
    d.train_hashed(labels, idx, val)
    jax.block_until_ready(d.state.w)

    # host-only portion: run everything except the device call
    with reg.span(f"flush.host.b{b}") as sp_host:
        for _ in range(REPS):
            slots = [d._ensure_label(lb) for lb in labels]
            for s in slots:
                d._dcounts[s] += 1.0
            sa = np.zeros(b, dtype=np.int32)
            sa[: len(slots)] = slots
            _ = d._mask()
    host_ms = sp_host.seconds / REPS * 1e3

    # dispatch (async) vs blocked step
    with reg.span(f"flush.dispatch.b{b}") as sp_disp:
        for _ in range(REPS):
            d.train_hashed(labels, idx, val)
    disp_ms = sp_disp.seconds / REPS * 1e3
    jax.block_until_ready(d.state.w)

    with reg.span(f"flush.step.b{b}") as sp_step:
        for _ in range(REPS):
            d.train_hashed(labels, idx, val)
            jax.block_until_ready(d.state.w)
    step_ms = sp_step.seconds / REPS * 1e3

    # pipelined: N dispatches then one block
    with reg.span(f"flush.pipe.b{b}") as sp_pipe:
        for _ in range(PIPE_DEPTH):
            d.train_hashed(labels, idx, val)
        jax.block_until_ready(d.state.w)
    pipe_ms = sp_pipe.seconds / PIPE_DEPTH * 1e3

    return {
        f"profile_flush_host_ms_b{b}": round(host_ms, 3),
        f"profile_flush_dispatch_ms_b{b}": round(disp_ms, 3),
        f"profile_flush_step_ms_b{b}": round(step_ms, 3),
        f"profile_flush_pipe_ms_b{b}": round(pipe_ms, 3),
        f"profile_flush_blocked_samples_per_sec_b{b}": round(
            b / step_ms * 1e3, 1),
        f"profile_flush_piped_samples_per_sec_b{b}": round(
            b / pipe_ms * 1e3, 1),
    }


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="profile_flush",
        description="phase breakdown of the train flush path, on the "
                    "tracing span plane; JSON output diffs with "
                    "tools/bench_compare.py")
    p.add_argument("--batches", default="512,2048,8192,32768",
                   help="comma-separated batch sizes")
    p.add_argument("--json", dest="json_path", default="",
                   help="write the flat metric map here "
                        "(bench_compare.py input)")
    p.add_argument("--device-dir", default="",
                   help="also capture an XLA device trace of the "
                        "measured loops into this directory "
                        "(TensorBoard-viewable)")
    ns = p.parse_args(argv)
    batches = [int(b) for b in ns.batches.split(",") if b.strip()]

    d = ClassifierDriver(CONF, dim_bits=18)
    reg = tracing.Registry()
    out = {"profile_flush_platform": jax.devices()[0].platform}
    print("platform:", out["profile_flush_platform"])
    with tracing.device_trace(ns.device_dir or None):
        for b in batches:
            keys = profile_batch(d, reg, b)
            out.update(keys)
            host = keys[f"profile_flush_host_ms_b{b}"]
            disp = keys[f"profile_flush_dispatch_ms_b{b}"]
            step = keys[f"profile_flush_step_ms_b{b}"]
            pipe = keys[f"profile_flush_pipe_ms_b{b}"]
            print(f"B={b:6d}  host={host:7.2f}ms  disp={disp:7.2f}ms  "
                  f"step={step:7.2f}ms  pipe={pipe:7.2f}ms  "
                  f"-> blocked "
                  f"{keys[f'profile_flush_blocked_samples_per_sec_b{b}']:9.0f}"
                  f"/s  piped "
                  f"{keys[f'profile_flush_piped_samples_per_sec_b{b}']:9.0f}"
                  f"/s")
    if ns.json_path:
        numeric = {k: v for k, v in out.items()
                   if isinstance(v, (int, float))}
        with open(ns.json_path, "w") as f:
            json.dump(numeric, f, indent=1, sort_keys=True)
            f.write("\n")
        print(f"wrote {len(numeric)} key(s) to {ns.json_path}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
