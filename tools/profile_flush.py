"""Profile the serving flush path: where does the per-flush time go?

Mimics service.py's train_raw flush at several batch sizes, separating:
  host   — _ensure_label loop + padding + slot array build
  disp   — jitted train_batch dispatch (async, no block)
  step   — device step time (dispatch..block_until_ready)
  pipe   — effective per-step time when N steps are dispatched back-to-back
           before one block (does the runtime pipeline them?)
"""
import time
import numpy as np

import jax

from jubatus_tpu.models.classifier import ClassifierDriver

CONF = {
    "method": "AROW",
    "parameter": {"regularization_weight": 1.0},
    "converter": {"num_rules": [{"key": "*", "type": "num"}]},
}
K = 32
rng = np.random.default_rng(0)


def make_batch(b):
    labels = ["a" if x < 0.5 else "b" for x in rng.random(b)]
    idx = rng.integers(0, 1 << 18, size=(b, K)).astype(np.int32)
    val = rng.normal(size=(b, K)).astype(np.float32)
    return labels, idx, val


def main():
    d = ClassifierDriver(CONF, dim_bits=18)
    print("platform:", jax.devices()[0].platform)
    for b in (512, 2048, 8192, 32768):
        labels, idx, val = make_batch(b)
        # warm the compile
        d.train_hashed(labels, idx, val)
        jax.block_until_ready(d.state.w)

        # host-only portion: run everything except the device call
        t0 = time.perf_counter()
        for _ in range(5):
            slots = [d._ensure_label(lb) for lb in labels]
            for s in slots:
                d._dcounts[s] += 1.0
            sa = np.zeros(b, dtype=np.int32)
            sa[: len(slots)] = slots
            _ = d._mask()
        host_ms = (time.perf_counter() - t0) / 5 * 1e3

        # dispatch (async) vs blocked step
        t0 = time.perf_counter()
        for _ in range(5):
            d.train_hashed(labels, idx, val)
        disp_ms = (time.perf_counter() - t0) / 5 * 1e3
        jax.block_until_ready(d.state.w)

        t0 = time.perf_counter()
        for _ in range(5):
            d.train_hashed(labels, idx, val)
            jax.block_until_ready(d.state.w)
        step_ms = (time.perf_counter() - t0) / 5 * 1e3

        # pipelined: 10 dispatches then one block
        t0 = time.perf_counter()
        for _ in range(10):
            d.train_hashed(labels, idx, val)
        jax.block_until_ready(d.state.w)
        pipe_ms = (time.perf_counter() - t0) / 10 * 1e3

        print(f"B={b:6d}  host={host_ms:7.2f}ms  disp={disp_ms:7.2f}ms  "
              f"step={step_ms:7.2f}ms  pipe={pipe_ms:7.2f}ms  "
              f"-> blocked {b/step_ms*1e3:9.0f}/s  piped {b/pipe_ms*1e3:9.0f}/s")


if __name__ == "__main__":
    main()
