"""All-round tunnel re-probe: the "cron-style second chance" bench.py promises.

The axon TPU tunnel wedges for hours at a time (a SIGKILL mid-device-op
holds the pool claim upstream; see docs/PERF_NOTES.md "tunnel wedge").
bench.py's probe ladder only runs at bench start, so a tunnel that
revives mid-round used to go unnoticed — two rounds of CPU-only
artifacts (VERDICT r4 "What's missing" #1). This runner closes that gap:

  * every PROBE_INTERVAL_S it asks a FRESH subprocess whether the tunnel
    answers (bench.probe_tunnel — one shared definition of "alive", one
    shared watchdog-thread child that is never killed mid-device-op);
  * every probe, success or failure, is appended as a timestamped JSON
    line to tools/reprobe_log_r{N}.jsonl — the durable evidence trail;
  * on the FIRST success it runs the full capture suite on the chip
    (bench.py, then tools/bench_scatter_dedup.py) and persists stdout/
    stderr under chip_capture_r{N}/, then keeps probing (a later wedge
    + revival gets a second capture slot, max CAPTURE_SLOTS).

Run it detached for the whole round:  python tools/tunnel_reprobe.py
It exits on its own after MAX_HOURS (default 11) so it never outlives
the round. Durable logging is the point — the reference logs its
per-round numbers durably too (linear_mixer.cpp:553-558).
"""

import json
import os
import signal
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

# benchlib is the jax-free slice of the bench plumbing: this process
# must never import the device stack (axon import hooks in a long-lived
# monitor defeat the keep-device-init-out-of-process design)
import benchlib  # noqa: E402

PROBE_INTERVAL_S = float(os.environ.get("JUBATUS_REPROBE_INTERVAL", "480"))
PROBE_TIMEOUT_S = float(os.environ.get("JUBATUS_REPROBE_TIMEOUT", "120"))
MAX_HOURS = float(os.environ.get("JUBATUS_REPROBE_MAX_HOURS", "11"))
CAPTURE_SLOTS = int(os.environ.get("JUBATUS_REPROBE_CAPTURES", "2"))


#: pids of capture children we SIGTERMed but had to abandon; a new
#: capture slot is withheld while any of these still runs (two benches
#: contending for the one tunnel would corrupt both captures)
_abandoned_pids = []


def orphans_alive() -> list:
    """The subset of abandoned capture pids that are still running."""
    alive = []
    for pid in _abandoned_pids:
        try:
            os.kill(pid, 0)
            alive.append(pid)
        except (ProcessLookupError, PermissionError):
            pass
    _abandoned_pids[:] = alive
    return alive


def run_abandonable(cmd, budget_s, out_path, log, name, env=None):
    """Run a capture member; on overrun SIGTERM it, then ABANDON it.

    Never SIGKILL: a SIGKILL mid-device-op is the exact tunnel-wedge
    trigger this tool exists to route around. The group TERM is safe by
    construction: bench.py (and its d24 child) defer SIGTERM to a phase
    boundary where no device op is in flight, and every other group
    member (bench_mix collective children, serving load generators) is
    CPU-only — scrub_child_env strips the axon site from their
    PYTHONPATH, so they cannot hold a tunnel op. If the child still
    won't die we leave it running as an orphan, record its pid so no
    new capture overlaps it, and move on — an orphaned bench is
    recoverable, a wedged tunnel is not."""
    t0 = time.time()
    with open(out_path, "w") as f:
        f.write(f"# cmd: {' '.join(cmd)}\n")
        f.flush()
        proc = subprocess.Popen(cmd, stdout=f, stderr=subprocess.STDOUT,
                                cwd=REPO, env=env, start_new_session=True)
        try:
            rc = proc.wait(timeout=budget_s)
        except subprocess.TimeoutExpired:
            # TERM the whole group: bench spawns servers, load-gen
            # clients and collective workers; start_new_session made the
            # child a group leader precisely so this reaches them all
            try:
                os.killpg(proc.pid, signal.SIGTERM)
            except (ProcessLookupError, PermissionError):
                proc.send_signal(signal.SIGTERM)
            try:
                rc = proc.wait(timeout=120)
            except subprocess.TimeoutExpired:
                _abandoned_pids.append(proc.pid)
                log({"event": f"capture_{name}", "abandoned_pid": proc.pid,
                     "wall_s": round(time.time() - t0, 1)})
                f.write(f"\n# ABANDONED after {budget_s}s + SIGTERM grace "
                        f"(pid {proc.pid} left running; no SIGKILL)\n")
                return
        f.write(f"\n# rc: {rc}  wall_s: {time.time() - t0:.1f}\n")
    log({"event": f"capture_{name}", "rc": rc,
         "wall_s": round(time.time() - t0, 1)})


def run_capture(slot: int, rnd: int, log, remaining_s: float) -> None:
    """Tunnel is up: run the full capture suite, persist everything.

    Budgets are clipped to the daemon's remaining lifetime so a capture
    begun near the deadline cannot outlive the round (and stomp the next
    round's artifacts)."""
    cap_dir = os.path.join(REPO, f"chip_capture_r{rnd:02d}")
    os.makedirs(cap_dir, exist_ok=True)
    suite = [
        # bench.py owns its own probe watchdogs + CPU fallback; its full
        # payload also lands in BENCH_FULL_r{N}.json (truncation-proof)
        ("bench", [sys.executable, os.path.join(REPO, "bench.py")], 3600),
        ("scatter_dedup",
         [sys.executable,
          os.path.join(REPO, "tools", "bench_scatter_dedup.py")], 1800),
    ]
    # pin the round label for the whole capture: if the driver ends the
    # round mid-capture (writing BENCH_r{N}.json), an unpinned bench
    # would relabel its BENCH_FULL as the NEXT round's
    env = dict(os.environ)
    env["JUBATUS_BENCH_ROUND"] = str(rnd)
    # a lingering cpu pin (wedge-debugging shells) must not burn a
    # capture slot on a CPU run — the probe pops it, so must the capture
    env.pop("JUBATUS_TPU_PLATFORM", None)
    t0 = time.time()
    for name, cmd, budget in suite:
        left = remaining_s - (time.time() - t0)
        if left < 300:
            log({"event": f"capture_{name}", "slot": slot,
                 "skipped": "deadline", "left_s": round(left, 1)})
            continue
        out_path = os.path.join(cap_dir, f"{name}_slot{slot}.txt")
        try:
            run_abandonable(cmd, min(budget, left - 150), out_path, log,
                            name, env=env)
        except Exception as e:  # noqa: BLE001
            log({"event": f"capture_{name}", "slot": slot,
                 "err": repr(e)[:160]})


def _foreign_bench_running() -> bool:
    """True when a bench.py we did not spawn is running (the driver's
    end-of-round capture, or an operator run)."""
    me = os.getpid()
    mine = set(_abandoned_pids)
    # inspect real argv, not a command-line substring: a `pgrep -f
    # "python.*bench\.py"` also matches any process whose cmdline merely
    # MENTIONS both words (e.g. the round driver's shell wrapper embeds
    # its whole instruction text), which deferred captures forever
    try:
        for pid_s in os.listdir("/proc"):
            if not pid_s.isdigit():
                continue
            pid = int(pid_s)
            if pid == me or pid in mine:
                continue
            try:
                with open(f"/proc/{pid}/cmdline", "rb") as f:
                    argv = f.read().split(b"\0")
            except OSError:
                continue
            if not argv or b"python" not in os.path.basename(argv[0]):
                continue
            if any(os.path.basename(a) == b"bench.py" for a in argv[1:]):
                return True
    except Exception:  # noqa: BLE001 — no /proc: assume clear
        pass
    return False


def main() -> None:
    # single-instance guard: overlapping daemons would run concurrent
    # bench captures that contend for the one tunnel and clobber each
    # other's artifacts; the lock dies with the process (flock semantics)
    import fcntl

    # "a" not "w": a LOSING instance must not truncate the holder's
    # recorded pid on its way out
    lock_f = open(os.path.join(REPO, "tools", ".tunnel_reprobe.lock"), "a")
    try:
        fcntl.flock(lock_f, fcntl.LOCK_EX | fcntl.LOCK_NB)
    except OSError:
        print("another tunnel_reprobe daemon holds the lock; exiting",
              file=sys.stderr)
        return
    lock_f.truncate(0)
    lock_f.write(str(os.getpid()))
    lock_f.flush()

    rnd = benchlib.current_round()
    log_path = os.path.join(REPO, "tools", f"reprobe_log_r{rnd:02d}.jsonl")
    deadline = time.time() + MAX_HOURS * 3600
    captures_done = 0
    # the second slot is for a wedge + REVIVAL, not a duplicate run on a
    # tunnel that stayed healthy: require an observed dead probe since
    # the last capture before granting another slot
    saw_dead_since_capture = True

    def log(rec: dict) -> None:
        rec = {"t": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()), **rec}
        with open(log_path, "a") as f:
            f.write(json.dumps(rec) + "\n")

    log({"event": "start", "interval_s": PROBE_INTERVAL_S,
         "max_hours": MAX_HOURS, "pid": os.getpid()})
    while time.time() < deadline:
        res = benchlib.probe_tunnel(PROBE_TIMEOUT_S)
        alive = benchlib.tunnel_is_alive(res)
        log({"event": "probe", "alive": alive, **res})
        if not alive:
            saw_dead_since_capture = True
        elif _foreign_bench_running():
            # the driver's end-of-round bench (or an operator run) owns
            # the tunnel right now; a concurrent capture would contend
            # for the one core + tunnel and skew both
            log({"event": "capture_deferred", "reason": "bench running"})
        elif orphans_alive():
            # an abandoned capture child is still running; launching
            # another bench against the one tunnel would corrupt both
            log({"event": "capture_deferred", "orphans": orphans_alive()})
        elif (captures_done < CAPTURE_SLOTS and saw_dead_since_capture
              and time.time() < deadline - 900):
            captures_done += 1
            saw_dead_since_capture = False
            log({"event": "capture_begin", "slot": captures_done})
            run_capture(captures_done, rnd, log, deadline - time.time())
            log({"event": "capture_end", "slot": captures_done})
        time.sleep(PROBE_INTERVAL_S)
    log({"event": "stop", "captures_done": captures_done})


if __name__ == "__main__":
    main()
